"""Shuffled batching + host→device prefetch.

The reference's input pipeline is a C++ queue graph: filename queue →
``FixedLengthRecordReader`` → per-record decode/crop → ``RandomShuffleQueue``
(``min_after_dequeue=5000``) drained 128 at a time by the train step, all fed
by background queue-runner threads (``cifar10cnn.py:72-91,223``). The
TPU-native equivalent keeps the same *contract* — an endless stream of
shuffled, decoded, cropped batches — but runs it as vectorized NumPy on the
host with a background prefetch thread that lands batches in device memory
ahead of the step, so the compiled step never blocks on input.

Shuffling note: the in-memory path shuffles by drawing from a fresh uniform
permutation each epoch — strictly *stronger* mixing than the reference's
bounded 5000-element shuffle buffer (``DataConfig.shuffle_buffer`` is kept
for the streaming native loader, where a bounded buffer is the right tool).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, List, NamedTuple, Optional

import numpy as np

from dml_cnn_cifar10_tpu.config import DataConfig
from dml_cnn_cifar10_tpu.data import download, records as rec


class Batch(NamedTuple):
    images: np.ndarray  # [B, crop_h, crop_w, C] float32
    labels: np.ndarray  # [B] int32


class DataPipelineError(RuntimeError):
    """A failure raised while drawing the next batch/chunk. The training
    loop wraps its data-seam exceptions in this so the run supervisor
    (``train/supervisor.py``) can classify them as recoverable — restore
    the last checkpoint, rebuild the pipeline, resume — instead of
    treating an input hiccup like a model bug."""


def _load_split(files: List[str], cfg: DataConfig):
    """Decode all shards once, as uint8 HWC (cast happens per batch)."""
    nlb = download.label_bytes(cfg)
    record_bytes = cfg.record_bytes + (nlb - 1)
    label_offset = nlb - 1  # CIFAR-100: fine label is the 2nd byte
    wide = download.wide_label(cfg)  # imagenet_synth: big-endian uint16
    imgs, labs = [], []
    for path in files:
        r = rec.read_record_file(path, record_bytes)
        i, l = rec.decode_records(r, cfg, label_offset=label_offset,
                                  dtype=np.uint8, wide_label=wide)
        imgs.append(i)
        labs.append(l)
    return np.concatenate(imgs, axis=0), np.concatenate(labs, axis=0)


class ShuffleBatchIterator:
    """Endless shuffled batches over an in-memory decoded split.

    Contract parity with ``tf.train.shuffle_batch`` (``cifar10cnn.py:85-90``):
    endless repetition, per-epoch reshuffle, fixed batch size. Like the
    reference, every worker sees all shards by default
    (``cifar10cnn.py:73-91`` has no per-worker sharding); ``shard``/
    ``num_shards`` adds the disjoint per-process split multi-host runs want.
    """

    def __init__(
        self,
        files: List[str],
        cfg: DataConfig,
        batch_size: int,
        train: bool = True,
        seed: int = 0,
        shard: int = 0,
        num_shards: int = 1,
        _arrays=None,
    ):
        self.cfg = cfg
        self.batch_size = batch_size
        self.train = train
        self.rng = np.random.default_rng(seed)
        if _arrays is not None:
            images, labels = _arrays
        else:
            images, labels = _load_split(files, cfg)
        # Pre-shard total, the denominator for distributed full-split eval.
        self.total_records = images.shape[0]
        self.num_shards = num_shards
        if num_shards > 1:
            images, labels = images[shard::num_shards], labels[shard::num_shards]
        self.images, self.labels = images, labels
        self.n = images.shape[0]
        self._perm = self.rng.permutation(self.n)
        self._cursor = 0

    def clone(self, seed: int, train: Optional[bool] = None
              ) -> "ShuffleBatchIterator":
        """Second independent stream over the SAME decoded arrays (no extra
        host RAM) — e.g. the fresh-batch train-accuracy stream
        (``cifar10cnn.py:235``)."""
        it = ShuffleBatchIterator(
            [], self.cfg, self.batch_size,
            train=self.train if train is None else train,
            seed=seed, _arrays=(self.images, self.labels))
        it.total_records = self.total_records
        it.num_shards = self.num_shards
        return it

    def _next_indices(self, k: int) -> np.ndarray:
        out = np.empty(k, dtype=np.int64)
        filled = 0
        while filled < k:
            take = min(k - filled, self.n - self._cursor)
            out[filled : filled + take] = self._perm[
                self._cursor : self._cursor + take
            ]
            filled += take
            self._cursor += take
            if self._cursor == self.n:  # epoch boundary: reshuffle, repeat
                self._perm = self.rng.permutation(self.n)
                self._cursor = 0
        return out

    def _finish(self, images: np.ndarray) -> np.ndarray:
        """uint8 [N,H,W,C] → cropped/augmented/normalized float32 batch."""
        cfg = self.cfg
        images = images.astype(np.float32)
        if self.train and cfg.random_crop:
            images = rec.random_crop(images, cfg.crop_height, cfg.crop_width,
                                     self.rng)
        else:
            images = rec.center_crop(images, cfg.crop_height, cfg.crop_width)
        if self.train and cfg.random_flip:
            images = rec.random_flip(images, self.rng)
        if self.train and cfg.random_brightness:
            images = rec.random_brightness(images, cfg.random_brightness,
                                           self.rng)
        if self.train and cfg.random_contrast:
            images = rec.random_contrast(images, cfg.random_contrast,
                                         self.rng)
        return np.ascontiguousarray(rec.normalize(images, cfg.normalize))

    def __iter__(self) -> Iterator[Batch]:
        return self

    def __next__(self) -> Batch:
        idx = self._next_indices(self.batch_size)
        return Batch(self._finish(self.images[idx]), self.labels[idx])

    # True when next_index_chunk draws from the same stream as
    # __next__/next_raw_chunk. The native C++ iterator streams records by
    # value from its bounded pool (no index view), so it sets this False
    # and the resident data path is gated off (train/loop.py).
    supports_index_stream = True

    # True when skip_batches can fast-forward the stream — the basis of
    # exact-resume data order (train/loop.py). The native loader's C++
    # pool has no replayable draw stream, so it sets this False.
    supports_skip = True

    # The augmentations skip_batches knows how to replay. New fields in
    # DataConfig._AUG_OFF must get a mirror draw below (and coverage in
    # tests/test_exact_resume.py::test_skip_batches_matches_consumed_
    # stream) — skip_batches raises loudly otherwise, so drift between
    # _finish's draws and the replay can't be silent.
    _SKIP_MIRRORED_AUGS = frozenset(
        {"random_crop", "random_flip", "random_brightness",
         "random_contrast"})

    def skip_batches(self, n: int, aug: bool = False) -> None:
        """Fast-forward the stream by ``n`` batches WITHOUT materializing
        them: replays exactly the index draws (and, with ``aug=True``,
        the per-batch augmentation draws ``_finish`` makes on the
        host-decode path) so batch ``n`` after a skip is bit-identical
        to batch ``n`` of an unskipped same-seed iterator. This is how a
        resumed run continues the data stream where the previous run's
        CONSUMPTION stopped — prefetch lookahead regenerates, it is not
        part of the consumed position. tests/test_exact_resume.py::
        test_skip_batches_matches_consumed_stream pins the equivalence;
        keep the draw mirror in sync with ``_finish``."""
        cfg = self.cfg
        b = self.batch_size
        burn_aug = aug and self.train and cfg.augmented
        if not burn_aug:
            # No per-batch rng draws besides the index stream, and a
            # chunked draw is cursor-equivalent to n single draws (the
            # same equivalence next_index_chunk relies on). Draw at most
            # one epoch of indices at a time so resuming a 500k-step run
            # fast-forwards in O(dataset) memory, not O(consumed).
            remaining = b * n
            cap = max(self.n, 1)
            while remaining > 0:
                take = min(remaining, cap)
                self._next_indices(take)
                remaining -= take
            return
        active = {name for name, off in cfg._AUG_OFF
                  if getattr(cfg, name) != off}
        unmirrored = active - self._SKIP_MIRRORED_AUGS
        if unmirrored:
            raise NotImplementedError(
                f"skip_batches has no draw mirror for {sorted(unmirrored)}"
                " — add its rng replay here and to the exact-resume test"
                " before using it with exact resume")
        for _ in range(n):
            self._next_indices(b)
            if cfg.random_crop:
                self.rng.integers(
                    0, cfg.image_height - cfg.crop_height + 1, size=b)
                self.rng.integers(
                    0, cfg.image_width - cfg.crop_width + 1, size=b)
            if cfg.random_flip:
                self.rng.random(b)
            if cfg.random_brightness:
                self.rng.uniform(-cfg.random_brightness,
                                 cfg.random_brightness, b)
            if cfg.random_contrast:
                self.rng.uniform(1.0 - cfg.random_contrast,
                                 1.0 + cfg.random_contrast, b)

    def next_index_chunk(self, k: int) -> np.ndarray:
        """``[k, B]`` int32 shuffled indices into the local decoded arrays
        (``self.images``/``self.labels``) — the same stream as
        ``next_raw_chunk`` minus the gather, for the HBM-resident data path
        (``parallel/step.py:make_train_chunk_resident``) where the gather
        runs on device."""
        idx = self._next_indices(self.batch_size * k)
        return idx.reshape(k, self.batch_size).astype(np.int32)

    def next_raw_chunk(self, k: int) -> Batch:
        """``k`` stacked shuffled batches of RAW uint8 full-size images
        ([k, B, H, W, C] — no crop/cast/normalize) for device-side
        preprocessing (``ops/preprocess.py``). One fancy-index gather per
        chunk: the host's only per-chunk work is a byte memcpy."""
        idx = self._next_indices(self.batch_size * k)
        ims = self.images[idx].reshape(
            k, self.batch_size, *self.images.shape[1:])
        return Batch(ims, self.labels[idx].reshape(k, self.batch_size))

    def full_sweep(self) -> Iterator[Batch]:
        """Deterministic single pass over the local shard (variable-size
        final batch). For multi-process collective eval use
        :meth:`full_sweep_padded`."""
        for start in range(0, self.n, self.batch_size):
            stop = start + self.batch_size
            yield Batch(self._finish(self.images[start:stop]),
                        self.labels[start:stop])

    def num_padded_sweep_batches(self) -> int:
        """Number of fixed-size batches every process must contribute so a
        sharded full-split sweep issues the SAME number of collective steps
        on every host (strided shards differ by ≤1 record)."""
        max_shard = -(-self.total_records // max(self.num_shards, 1))
        return -(-max_shard // self.batch_size)

    def full_sweep_padded(self) -> Iterator[Batch]:
        """Fixed-shape single pass: every batch has exactly ``batch_size``
        rows, pad rows carry label -1 (never matches an argmax in [0, K), so
        they contribute 0 correct predictions). All processes yield the same
        batch count — safe to drive a collective eval step in lockstep."""
        for b in range(self.num_padded_sweep_batches()):
            start = min(b * self.batch_size, self.n)
            stop = min(start + self.batch_size, self.n)
            images = self._finish(self.images[start:stop])
            labels = self.labels[start:stop]
            pad = self.batch_size - images.shape[0]
            if pad:
                images = np.pad(images,
                                ((0, pad), (0, 0), (0, 0), (0, 0)))
                labels = np.pad(labels, (0, pad), constant_values=-1)
            yield Batch(images, labels)


class PrefetchIterator:
    """Background-thread prefetch: overlap host batching + device transfer
    with the running step (the queue-runner role, ``cifar10cnn.py:223``).

    ``place`` maps a host :class:`Batch` to device arrays (e.g.
    ``jax.device_put`` with a NamedSharding); it runs on the prefetch thread
    so H2D transfer overlaps compute.
    """

    _DONE = object()

    def __init__(self, it: Iterator[Batch], depth: int = 2,
                 place: Optional[Callable] = None):
        self._it = it
        self._place = place or (lambda b: b)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that re-checks the stop flag — never parks forever."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            for item in self._it:
                if self._stop.is_set() or not self._put(self._place(item)):
                    return
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            if not self._stop.is_set():
                self._put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self):
        """Stop the producer and join it (drains so its pending put can
        observe the stop flag)."""
        self._stop.set()
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)


def input_pipeline(
    cfg: DataConfig,
    batch_size: int,
    train: bool = True,
    seed: int = 0,
    shard: int = 0,
    num_shards: int = 1,
) -> ShuffleBatchIterator:
    """Build the batch iterator for the train or test split.

    Parity entrypoint for ``input_pipeline(batch_size, train_logical)``
    (``cifar10cnn.py:72-91``). Note the reference shuffle-batches the *test*
    split too — eval draws random test batches — so this does the same; use
    :meth:`ShuffleBatchIterator.full_sweep_padded` for proper full-test-set
    eval.
    """
    download.ensure_dataset(cfg)
    files = download.train_files(cfg) if train else download.test_files(cfg)
    if cfg.use_native_loader:
        try:
            from dml_cnn_cifar10_tpu.data import native
            return native.NativeShuffleBatchIterator(
                files, cfg, batch_size, train=train, seed=seed,
                shard=shard, num_shards=num_shards)
        except Exception:
            pass  # library not built — NumPy reference path
    return ShuffleBatchIterator(
        files, cfg, batch_size, train=train, seed=seed,
        shard=shard, num_shards=num_shards)
