"""ctypes bindings for the native record loader (runtime/recordio.cc).

The C++ side replaces the reference's input-queue runtime — file-order
shuffling, fixed-length record reads, the bounded RandomShuffleQueue
(``min_after_dequeue=5000, capacity=5000+3*batch``,
``cifar10cnn.py:85-90``), and the CHW→HWC decode — all off the GIL on a
producer thread. Python keeps only the batched crop/augment/normalize step
(vectorized NumPy) and the host→device prefetch.

Fidelity note: this is the path that reproduces the reference's *bounded*
shuffle semantics exactly; the pure-NumPy fallback
(:class:`~dml_cnn_cifar10_tpu.data.pipeline.ShuffleBatchIterator`) uses
full-permutation shuffling (strictly stronger mixing). Tests cover both.

The shared library is built on demand with ``make -C runtime`` (g++ only,
no pybind11 — plain C ABI + ctypes).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

from dml_cnn_cifar10_tpu.config import DataConfig
from dml_cnn_cifar10_tpu.data import download
from dml_cnn_cifar10_tpu.data import pipeline as pipe
from dml_cnn_cifar10_tpu.data import records as rec

_RUNTIME_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "runtime")
_LIB_PATH = os.path.join(_RUNTIME_DIR, "librecordio.so")

_lib = None
_lib_lock = threading.Lock()


def _build_library() -> None:
    # Serialize concurrent builders (multi-process tests on one box): a
    # relink racing another process's dlopen would hand out a truncated
    # .so. fcntl lock on a sidecar file; make itself is then idempotent.
    import fcntl
    lock_path = os.path.join(_RUNTIME_DIR, ".build.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        subprocess.run(["make", "-C", _RUNTIME_DIR], check=True,
                       capture_output=True)


def _needs_build() -> bool:
    """True when the .so is missing or older than its sources. The
    timestamp check lives HERE (not in an unconditional make) so a host
    with a prebuilt .so and no toolchain never shells out — but a stale
    binary after a recordio.cc edit still rebuilds (loading it against
    newer argtypes would silently mis-decode)."""
    if not os.path.exists(_LIB_PATH):
        return True
    so_mtime = os.path.getmtime(_LIB_PATH)
    for src in ("recordio.cc", "Makefile"):
        path = os.path.join(_RUNTIME_DIR, src)
        if os.path.exists(path) and os.path.getmtime(path) > so_mtime:
            return True
    return False


def load_library() -> ctypes.CDLL:
    """Load (building if needed) librecordio.so; raises on failure."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            _build_library()
        lib = ctypes.CDLL(_LIB_PATH)
        # ABI handshake before any argtypes are trusted: the mtime-based
        # rebuild heuristic can miss (prebuilt .so shipped without
        # sources, or mtimes not newer), and a stale library would
        # silently mis-bind recordio_create's arguments — e.g. dropping
        # label_wide decodes imagenet_synth labels as their low byte
        # only: silently wrong training data.
        expected_abi = 2
        try:
            lib.recordio_abi_version.restype = ctypes.c_int64
            got = int(lib.recordio_abi_version())
        except AttributeError:
            got = 1  # pre-versioning builds had no such symbol
        if got != expected_abi:
            raise RuntimeError(
                f"librecordio.so ABI v{got} != expected v{expected_abi} "
                f"at {_LIB_PATH}: stale prebuilt library — rebuild with "
                f"`make -C runtime` (or delete the .so to rebuild on "
                f"demand)")
        lib.recordio_create.restype = ctypes.c_void_p
        lib.recordio_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.recordio_next_batch.restype = ctypes.c_int
        lib.recordio_next_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.recordio_error.restype = ctypes.c_char_p
        lib.recordio_error.argtypes = [ctypes.c_void_p]
        lib.recordio_buffered.restype = ctypes.c_int64
        lib.recordio_buffered.argtypes = [ctypes.c_void_p]
        lib.recordio_destroy.restype = None
        lib.recordio_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeShuffleBatchIterator(pipe.ShuffleBatchIterator):
    """Streaming batches from the C++ loader.

    Subclasses the NumPy iterator so the sweep/eval/clone contract (backed
    by the in-memory decoded split) is shared; ``__next__`` — the training
    hot path — streams from the native bounded shuffle pool instead of the
    in-memory permutation.
    """

    def __init__(self, files: List[str], cfg: DataConfig, batch_size: int,
                 train: bool = True, seed: int = 0, shard: int = 0,
                 num_shards: int = 1):
        lib = load_library()  # raise *before* any base-class work
        super().__init__(files, cfg, batch_size, train=train, seed=seed,
                         shard=shard, num_shards=num_shards)
        # Per-process shard of the file list (multi-host): strided like the
        # record-level sharding of the base class. With fewer files than
        # shards every process reads everything (the reference's behavior —
        # no sharding at all, cifar10cnn.py:73-91).
        if num_shards > 1 and len(files) >= num_shards:
            files = files[shard::num_shards]
        self._lib = lib
        nlb = download.label_bytes(cfg)
        record_bytes = cfg.record_bytes + (nlb - 1)
        capacity = cfg.shuffle_buffer + 3 * batch_size  # cifar10cnn.py:86
        paths = b"\0".join(p.encode() for p in files) + b"\0"
        self._handle = lib.recordio_create(
            paths, len(files), record_bytes, nlb, nlb - 1,
            cfg.image_height, cfg.image_width, cfg.num_channels,
            min(cfg.shuffle_buffer, capacity), capacity,
            np.uint64(seed * 2654435761 + 97531 + shard),
            int(download.wide_label(cfg)))
        if not self._handle:
            raise RuntimeError("recordio_create failed (bad geometry?)")
        self._img_buf = np.empty(
            (batch_size, cfg.image_height, cfg.image_width,
             cfg.num_channels), np.uint8)
        self._lab_buf = np.empty((batch_size,), np.int32)

    # The C++ pool streams records by VALUE (bounded-shuffle parity with
    # the reference's RandomShuffleQueue); it has no index view into the
    # decoded arrays, so the HBM-resident path can't reproduce its stream.
    supports_index_stream = False
    # The C++ pool's draw stream is not replayable from Python.
    supports_skip = False

    def next_index_chunk(self, k: int):
        raise NotImplementedError(
            "the native bounded-shuffle stream has no index view; use the "
            "raw-chunk path, or use_native_loader=False for the "
            "HBM-resident path")

    def _fill(self, img_buf: np.ndarray, lab_buf: np.ndarray) -> None:
        """One ``recordio_next_batch`` into caller buffers (shared by the
        per-batch and raw-chunk paths)."""
        if not self._handle:
            raise RuntimeError("native loader is closed")
        ret = self._lib.recordio_next_batch(
            self._handle, self.batch_size,
            img_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            lab_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if ret != 0:
            raise RuntimeError(
                "native loader: "
                + self._lib.recordio_error(self._handle).decode())

    def __next__(self) -> pipe.Batch:
        self._fill(self._img_buf, self._lab_buf)
        return pipe.Batch(self._finish(self._img_buf),
                          self._lab_buf.copy())

    def next_raw_chunk(self, k: int) -> pipe.Batch:
        """``k`` stacked raw uint8 batches straight from the native bounded
        shuffle pool (same stream as ``__next__``, no decode) — the chunked
        training path's input, keeping the reference's bounded-shuffle
        semantics instead of the base class's in-memory permutation."""
        cfg = self.cfg
        ims = np.empty((k, self.batch_size, cfg.image_height,
                        cfg.image_width, cfg.num_channels), np.uint8)
        lbs = np.empty((k, self.batch_size), np.int32)
        for j in range(k):
            self._fill(ims[j], lbs[j])
        return pipe.Batch(ims, lbs)

    def buffered(self) -> int:
        """Records currently in the native shuffle pool (observability)."""
        if not self._handle:
            raise RuntimeError("native loader is closed")
        return int(self._lib.recordio_buffered(self._handle))

    def close(self) -> None:
        handle, self._handle = getattr(self, "_handle", None), None
        if handle:
            self._lib.recordio_destroy(handle)

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
