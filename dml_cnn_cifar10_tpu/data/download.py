"""Dataset acquisition: download-or-reuse, plus an offline synthetic mode.

Mirrors ``download_data`` (``cifar10cnn.py:34-52``): fetch
``cifar-10-binary.tar.gz`` from cs.toronto.edu with a progress callback,
extract into ``<data_dir>/cifar-10-batches-bin``, and skip the download when
the tarball is already present. Additionally supports CIFAR-100 (same binary
framing, 1 coarse + 1 fine label byte) and a fully offline *synthetic* mode
that writes files in the exact CIFAR binary layout so every downstream stage
(reader, shuffle buffer, crop, training) is exercised without network access.
"""

from __future__ import annotations

import hashlib
import os
import tarfile
import time
import urllib.error
import urllib.request
from typing import List, Optional

import numpy as np

from dml_cnn_cifar10_tpu.config import DataConfig

CIFAR10_URL = "http://www.cs.toronto.edu/~kriz/cifar-10-binary.tar.gz"
CIFAR100_URL = "http://www.cs.toronto.edu/~kriz/cifar-100-binary.tar.gz"
# Published size/md5 of the archives — verified BEFORE extraction so a
# truncated or tampered download is caught at the byte layer instead of
# surfacing later as a record-framing decode error mid-training.
KNOWN_ARCHIVES = {
    CIFAR10_URL: {"bytes": 170052171,
                  "md5": "c32a1d4ab5d03f1284b67883e8d87530"},
    CIFAR100_URL: {"bytes": 169001437,
                   "md5": "03b5dce01913d631647c71ecec9e9cb8"},
}


class DownloadError(RuntimeError):
    """Dataset acquisition failed after bounded retries. ``fault`` names
    the class — ``"network"`` (unreachable/timeout) or ``"integrity"``
    (bad size/checksum/archive) — so ``ensure_dataset`` can report WHY
    it degraded to synthetic data."""

    def __init__(self, fault: str, msg: str):
        super().__init__(msg)
        self.fault = fault
CIFAR10_FOLDER = "cifar-10-batches-bin"   # extract_folder (cifar10cnn.py:27)
CIFAR100_FOLDER = "cifar-100-binary"
# ImageNet-shaped synthetic rung (BASELINE.json configs[3] — "ResNet-50 on
# ImageNet-1k"): same fixed-length binary framing at configurable geometry
# (e.g. 256x256x3, 1000 classes). >255 classes no longer fit CIFAR's single
# label byte, so these records lead with a 2-byte BIG-ENDIAN label
# (wide_label below). ImageNet itself has no binary-record distribution and
# this box has no egress; the shards are always generated synthetically.
IMAGENET_SYNTH_FOLDER = "imagenet-synth-bin"


def _progress(url: str):
    # Console progress bar, same format as cifar10cnn.py:47-49.
    def cb(block_num, block_size, total_size):
        pct = float(block_num * block_size) / float(max(total_size, 1)) * 100.0
        print("\r Downloading {} - {:.2f}%".format(url, pct), end="")
    return cb


def _fetch(url: str, dest: str, timeout: float) -> None:
    """One bounded-timeout download attempt, atomic (tmp + rename) so a
    dropped connection can never leave a half tarball that a later run
    would treat as already-downloaded (the reference's exact trap,
    ``cifar10cnn.py:43-44``)."""
    tmp = dest + ".tmp"
    cb = _progress(url)
    with urllib.request.urlopen(url, timeout=timeout) as r, \
            open(tmp, "wb") as f:
        total = int(r.headers.get("Content-Length") or 0)
        block = 1 << 16
        n = 0
        while True:
            chunk = r.read(block)
            if not chunk:
                break
            f.write(chunk)
            n += 1
            cb(n, block, total)
    print()
    os.replace(tmp, dest)


def _verify_archive(url: str, path: str) -> Optional[str]:
    """Failure reason when ``path`` mismatches the published size/md5 of
    ``url``; None when it matches (or the URL has no published record)."""
    want = KNOWN_ARCHIVES.get(url)
    if want is None:
        return None
    size = os.path.getsize(path)
    if size != want["bytes"]:
        return f"size {size} != expected {want['bytes']}"
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    if h.hexdigest() != want["md5"]:
        return f"md5 {h.hexdigest()} != expected {want['md5']}"
    return None


def download_and_extract(data_dir: str, url: str, retries: int = 3,
                         timeout: float = 30.0,
                         backoff_s: float = 1.0) -> str:
    """Fetch + verify + untar ``url`` into ``data_dir``, with bounded
    retry/backoff around the network and integrity steps.

    Unlike the reference (which skips extraction whenever the tarball exists,
    ``cifar10cnn.py:43-44`` — leaving a half-extracted dir broken forever),
    extraction re-runs whenever this is called: callers only call it when
    the target .bin files are missing. A tarball that fails its size/md5
    check is deleted and re-fetched; exhausted retries raise a
    classified :class:`DownloadError`.
    """
    os.makedirs(data_dir, exist_ok=True)
    data_file = os.path.join(data_dir, os.path.basename(url))
    last: Optional[BaseException] = None
    fault = "network"
    for attempt in range(max(1, retries)):
        if attempt:
            time.sleep(min(backoff_s * 2 ** (attempt - 1), 30.0))
        if not os.path.isfile(data_file):
            try:
                _fetch(url, data_file, timeout)
            except (urllib.error.URLError, OSError) as e:
                # URLError covers HTTP errors and DNS failures; OSError
                # covers socket timeouts/resets. Anything else is a bug
                # and propagates.
                last, fault = e, "network"
                print(f"\n[data] download attempt {attempt + 1}/"
                      f"{retries} failed: {e!r}")
                continue
        bad = _verify_archive(url, data_file)
        if bad:
            last, fault = DownloadError("integrity", bad), "integrity"
            print(f"[data] archive failed verification ({bad}); "
                  f"deleting and re-fetching")
            os.remove(data_file)
            continue
        try:
            tarfile.open(data_file, "r:gz").extractall(data_dir)
        except (tarfile.TarError, EOFError) as e:
            # Undetectable-by-table corruption (unknown URL, or a stale
            # pre-verification tarball): treat like an integrity failure
            # and re-fetch.
            last, fault = e, "integrity"
            print(f"[data] extraction failed ({e!r}); deleting the "
                  f"archive and re-fetching")
            os.remove(data_file)
            continue
        return data_dir
    raise DownloadError(
        fault, f"failed to acquire {url} after {retries} attempts; "
               f"last error: {last!r}") from last


def train_files(cfg: DataConfig) -> List[str]:
    """Training shards. CIFAR-10: ``data_batch_{1..5}.bin`` (cifar10cnn.py:78)."""
    if cfg.dataset in ("cifar10", "synthetic"):
        base = os.path.join(cfg.data_dir, CIFAR10_FOLDER)
        return [os.path.join(base, f"data_batch_{i}.bin") for i in range(1, 6)]
    if cfg.dataset == "cifar100":
        return [os.path.join(cfg.data_dir, CIFAR100_FOLDER, "train.bin")]
    if cfg.dataset == "imagenet_synth":
        base = os.path.join(cfg.data_dir, IMAGENET_SYNTH_FOLDER)
        return [os.path.join(base, f"train_{i}.bin") for i in range(1, 5)]
    raise ValueError(f"unknown dataset {cfg.dataset!r}")


def test_files(cfg: DataConfig) -> List[str]:
    """Test shard: ``test_batch.bin`` (cifar10cnn.py:80)."""
    if cfg.dataset in ("cifar10", "synthetic"):
        return [os.path.join(cfg.data_dir, CIFAR10_FOLDER, "test_batch.bin")]
    if cfg.dataset == "cifar100":
        return [os.path.join(cfg.data_dir, CIFAR100_FOLDER, "test.bin")]
    if cfg.dataset == "imagenet_synth":
        return [os.path.join(cfg.data_dir, IMAGENET_SYNTH_FOLDER, "val.bin")]
    raise ValueError(f"unknown dataset {cfg.dataset!r}")


def label_bytes(cfg: DataConfig) -> int:
    """CIFAR-10 records lead with 1 label byte; CIFAR-100 with 2
    (coarse+fine); imagenet_synth with 2 (one big-endian uint16)."""
    return 2 if cfg.dataset in ("cifar100", "imagenet_synth") else 1


def wide_label(cfg: DataConfig) -> bool:
    """True when the 2 leading label bytes encode ONE big-endian uint16
    (class counts past 255) rather than CIFAR-100's coarse+fine byte
    pair."""
    return cfg.dataset == "imagenet_synth"


def generate_synthetic_dataset(cfg: DataConfig, seed: int = 0) -> None:
    """Write CIFAR-layout binary files with class-separable random images.

    Byte layout per record is identical to the real dataset (label byte(s) +
    CHW uint8 image, ``cifar10cnn.py:24-25,58-62``). Images are Gaussian noise
    around a per-class mean color so a real model can overfit them — that lets
    integration tests assert "loss decreases / accuracy beats chance" offline.
    """
    rng = np.random.default_rng(seed)
    nlb = label_bytes(cfg)
    wide = wide_label(cfg)
    img_len = cfg.image_height * cfg.image_width * cfg.num_channels
    # One per-class mean-color table for the WHOLE dataset (train and test
    # shards must share the class→color mapping or nothing generalizes).
    means = rng.integers(30, 226, size=(cfg.num_classes, cfg.num_channels))

    def write(path: str, n: int) -> None:
        # Skip only when the existing file matches the REQUESTED geometry
        # and record count — a stale shard generated under different
        # --image_size/--crop_size/--synthetic_*_records would otherwise
        # be silently reused and mis-decoded downstream.
        want_bytes = n * (nlb + img_len)
        if os.path.isfile(path) and os.path.getsize(path) == want_bytes:
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            # Bounded chunks: one float32 normal draw per chunk instead of
            # a whole-shard float64 array (tens of GB at ImageNet
            # geometry).
            step = max(1, min(n, (64 << 20) // max(img_len, 1)))
            for lo in range(0, n, step):
                m = min(step, n - lo)
                labels = rng.integers(0, cfg.num_classes, size=m,
                                      dtype=np.int32)
                recs = np.empty((m, nlb + img_len), dtype=np.uint8)
                if wide:
                    # big-endian uint16
                    recs[:, 0] = (labels >> 8).astype(np.uint8)
                    recs[:, 1] = (labels & 0xFF).astype(np.uint8)
                else:
                    for lb in range(nlb):
                        # coarse == fine for synthetic CIFAR-100
                        recs[:, lb] = labels.astype(np.uint8)
                chw = rng.normal(
                    means[labels][:, :, None, None], 40.0,
                    size=(m, cfg.num_channels, cfg.image_height,
                          cfg.image_width)).astype(np.float32)
                recs[:, nlb:] = np.clip(chw, 0, 255).astype(
                    np.uint8).reshape(m, img_len)
                f.write(recs.tobytes())
        os.replace(tmp, path)

    per_shard = max(1, cfg.synthetic_train_records // len(train_files(cfg)))
    for path in train_files(cfg):
        write(path, per_shard)
    for path in test_files(cfg):
        write(path, cfg.synthetic_test_records)


def ensure_dataset(cfg: DataConfig) -> None:
    """Make sure the binary shards exist: download, or synthesize offline.

    Parity entrypoint for ``download_data()`` (``cifar10cnn.py:34-52``). In
    ``synthetic`` mode (or when the download fails — e.g. an air-gapped host)
    it falls back to :func:`generate_synthetic_dataset`.
    """
    if cfg.dataset in ("synthetic", "imagenet_synth"):
        # imagenet_synth is generate-only: ImageNet has no fixed-length
        # binary distribution to download; the rung's record framing is
        # this framework's own (wide labels + configurable geometry).
        generate_synthetic_dataset(cfg, seed=cfg.seed)
        return
    needed = train_files(cfg) + test_files(cfg)
    if all(os.path.isfile(p) for p in needed):
        return
    url = CIFAR100_URL if cfg.dataset == "cifar100" else CIFAR10_URL
    try:
        download_and_extract(cfg.data_dir, url)
    except DownloadError as e:
        # Only classified acquisition failures (network unreachable,
        # integrity exhausted) degrade to synthetic data — and the
        # warning names which class, so an air-gapped box and a
        # corrupted mirror are distinguishable in the logs. Anything
        # else (disk full, permission, a bug) propagates loudly.
        print(f"[data] {e.fault} failure acquiring {url} ({e}); "
              f"generating synthetic CIFAR-format data instead")
        generate_synthetic_dataset(cfg, seed=cfg.seed)
