"""Host-side input pipeline.

Replaces the reference's TF queue runtime — ``string_input_producer`` →
``FixedLengthRecordReader`` → ``shuffle_batch`` with its background
queue-runner threads (``cifar10cnn.py:54-91,223``) — with an explicit
host-side loader: mmap'd record files, a shuffle buffer, NumPy decode/crop,
and a double-buffered host→device prefetcher. On TPU the goal is identical:
keep the chip fed so the compiled step never waits on input.
"""

from dml_cnn_cifar10_tpu.data.download import (  # noqa: F401
    ensure_dataset,
    generate_synthetic_dataset,
    train_files,
    test_files,
)
from dml_cnn_cifar10_tpu.data.records import (  # noqa: F401
    read_record_file,
    decode_records,
)
from dml_cnn_cifar10_tpu.data.pipeline import (  # noqa: F401
    Batch,
    input_pipeline,
    ShuffleBatchIterator,
    PrefetchIterator,
)
