"""Device-side shuffled index generation — the host-free data stream.

The HBM-resident training path (`parallel/step.py:make_train_chunk_resident`)
eliminated per-chunk image traffic, but the round-3 headline still uploaded
a host-generated shuffled index array every dispatch
(`train/loop.py:produce`) — round-3 verdict #4 asked for the host to leave
the training data path entirely. This module makes the shuffled row index
for any (seed, global position) a PURE FUNCTION computed on device inside
the compiled chunk, so a training dispatch moves NOTHING host→device.

Design: a per-epoch pseudo-random permutation via a cycle-walking Feistel
network over the next even-bit power-of-two domain — the standard
counter-based (stateless) shuffle:

- bijective on [0, n) by construction (Feistel is invertible; cycle
  walking re-applies it until the image lands back inside [0, n), which
  preserves bijectivity on the subdomain), so every epoch visits every
  record exactly once, like the host path's ``rng.permutation(n)``;
- keyed on (seed, epoch): a fresh permutation every epoch;
- stateless: exact-resume needs NO sidecar — the stream position IS
  ``state.step`` (reference semantics: one batch per global step,
  ``cifar10cnn.py:29``'s global step drives everything), and every
  process computes identical values (multi-host safe by purity).

The host path (`data/pipeline.py:_next_indices`) keeps numpy-PCG
permutations; the two streams are equally-valid shuffles but NOT
bit-identical — switching ``--device_index_stream`` mid-run changes the
data order (documented at the flag).

Supported range: stream positions are computed in uint32 because the
Feistel/mix arithmetic requires it — the lowbias32 round function and
the cycle-walk domain are defined over exactly 2^32 (the multiply/xor
constants and shift widths are 32-bit), so the stream is exact for the
first ``2^32`` SAMPLES (step·batch + i < 2^32); past that the position
wraps silently, restarting the epoch sequence. ~4.3 B samples is ~86 k CIFAR
epochs — far past any real run here, but callers must enforce it:
:func:`check_supported_range` raises at BUILD time from the planned
``total_steps × batch`` (train/loop.py calls it when the stream is
enabled; round-4 advisor).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_C0 = jnp.uint32(0x9E3779B9)
_C1 = jnp.uint32(0x85EBCA6B)
_C2 = jnp.uint32(0xC2B2AE35)

_ROUNDS = 4


def _mix(x: jax.Array) -> jax.Array:
    """lowbias32 integer hash (uint32 → uint32) — the Feistel round
    function's mixer; runs as a handful of VPU int ops."""
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _feistel(pos: jax.Array, key: jax.Array, half_bits: int) -> jax.Array:
    """One balanced-Feistel pass over a ``2*half_bits``-bit domain."""
    mask = jnp.uint32((1 << half_bits) - 1)
    hi = pos >> half_bits
    lo = pos & mask
    for r in range(_ROUNDS):
        f = _mix(lo ^ _mix(key ^ (jnp.uint32(r) * _C2))) & mask
        hi, lo = lo, hi ^ f
    return (hi << half_bits) | lo


def _positions_to_rows(seed: int, j0: jax.Array, count: int,
                       n: int) -> jax.Array:
    """``[count]`` int32 rows of the infinite shuffled stream
    ``perm_0 ++ perm_1 ++ …`` at positions ``j0 .. j0+count-1``, where
    ``perm_e`` is the epoch-``e`` pseudo-permutation of ``[0, n)``."""
    if n <= 0:
        raise ValueError(f"need a positive dataset size, got {n}")
    bits = max(2, (n - 1).bit_length())
    bits += bits % 2                      # balanced halves
    half_bits = bits // 2
    domain = jnp.uint32(1 << bits)

    j = jnp.uint32(j0) + jnp.arange(count, dtype=jnp.uint32)
    epoch = j // jnp.uint32(n)
    pos = j % jnp.uint32(n)
    key = _mix(jnp.uint32(seed) * _C0 ^ epoch * _C1)
    out = _feistel(pos, key, half_bits)

    # Cycle walking: values that landed in [n, 2^bits) re-walk until they
    # fall inside [0, n). The domain is < 4n, so each walk escapes with
    # probability > 3/4; the loop converges in a couple of iterations.
    def cond(o):
        return jnp.any(o >= jnp.uint32(n))

    def walk(o):
        return jnp.where(o >= jnp.uint32(n), _feistel(o, key, half_bits)
                         % domain, o)

    out = jax.lax.while_loop(cond, walk, out)
    return out.astype(jnp.int32)


def check_supported_range(total_steps: int, batch: int) -> None:
    """Raise if a planned run would walk the stream past the uint32
    position domain (the silent-wrap hazard — module docstring)."""
    if total_steps * batch >= 1 << 32:
        raise ValueError(
            f"device index stream positions are uint32: total_steps="
            f"{total_steps} x batch={batch} = {total_steps * batch} "
            f"samples >= 2^32 would wrap the stream position and repeat "
            f"the epoch sequence. Use --device_index_stream=false for "
            f"runs this long.")


def epoch_shuffle_indices(seed: int, step: jax.Array, batch: int,
                          n: int) -> jax.Array:
    """``[batch]`` int32 dataset rows for global ``step`` — one batch of
    the stream (position ``step · batch``)."""
    return _positions_to_rows(seed, jnp.uint32(step) * jnp.uint32(batch),
                              batch, n)


def chunk_shuffle_indices(seed: int, step0: jax.Array, batch: int, k: int,
                          n: int) -> jax.Array:
    """``[k, batch]`` int32 rows for steps ``step0 .. step0+k-1`` — the
    whole chunk's indices in ONE vectorized call, so the resident chunk
    keeps its single whole-chunk gather + vectorized decode (a per-step
    in-scan gather measured ~10 % slower end to end on the v5e)."""
    flat = _positions_to_rows(seed,
                              jnp.uint32(step0) * jnp.uint32(batch),
                              batch * k, n)
    return flat.reshape(k, batch)
