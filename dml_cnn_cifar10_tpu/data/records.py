"""Fixed-length CIFAR record decoding on the host.

Replaces ``FixedLengthRecordReader`` + ``decode_raw`` + slice/reshape/
transpose (``cifar10cnn.py:54-70``) with vectorized NumPy over the whole
file: read bytes → ``[N, record_bytes]`` view → label byte(s) + CHW uint8
image → HWC float32. Crop/augmentation happens batched in the pipeline, not
per record. When the native C++ loader (``runtime/recordio.cc``) is built,
file reading + shuffle batching run there instead; this module is the
reference implementation and the fallback.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from dml_cnn_cifar10_tpu.config import DataConfig


def read_record_file(path: str, record_bytes: int) -> np.ndarray:
    """Read a binary shard into a ``[N, record_bytes]`` uint8 array.

    Trailing partial records (corrupt file) are dropped, matching the
    fixed-length reader's behavior.
    """
    raw = np.fromfile(path, dtype=np.uint8)
    n = raw.size // record_bytes
    return raw[: n * record_bytes].reshape(n, record_bytes)


def decode_records(
    records: np.ndarray, cfg: DataConfig, label_offset: int = 0,
    dtype=np.float32, wide_label: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """uint8 records → (images [N,H,W,C] ``dtype``, labels [N] int32).

    Mirrors ``read_cifar_files`` (``cifar10cnn.py:54-66``): byte
    ``label_offset`` is the label (CIFAR-100 fine label lives at offset 1),
    the remaining bytes are a CHW image transposed to HWC. The reference
    casts to float32 with no normalization (raw 0..255 values); the pipeline
    stores uint8 (4x less host RAM) and defers the cast to batch assembly.
    ``wide_label``: the first TWO bytes are one big-endian uint16 label
    (class counts past 255 — the imagenet_synth framing).
    """
    nlb = records.shape[1] - cfg.image_height * cfg.image_width * cfg.num_channels
    if wide_label:
        labels = ((records[:, 0].astype(np.int32) << 8)
                  | records[:, 1].astype(np.int32))
    else:
        labels = records[:, label_offset].astype(np.int32)
    chw = records[:, nlb:].reshape(
        -1, cfg.num_channels, cfg.image_height, cfg.image_width
    )
    # order="C": astype's default order="K" would mimic the transposed
    # (strided) memory layout, and every downstream gather/H2D of such an
    # array is a strided copy (measured ~37x slower device transfer).
    images = chw.transpose(0, 2, 3, 1).astype(dtype, order="C")
    return images, labels


def center_crop(images: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Deterministic center crop (pad if smaller).

    Parity with ``tf.image.resize_image_with_crop_or_pad``
    (``cifar10cnn.py:68``) — despite the "Randomly Crop" comment there, the
    TF op is a center crop. TF floors the top/left offset ((in-out)//2).
    """
    n, h, w, c = images.shape
    if out_h > h or out_w > w:
        ph, pw = max(out_h - h, 0), max(out_w - w, 0)
        images = np.pad(
            images,
            ((0, 0), (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0)),
        )
        n, h, w, c = images.shape
    top, left = (h - out_h) // 2, (w - out_w) // 2
    return images[:, top : top + out_h, left : left + out_w, :]


def random_crop(
    images: np.ndarray, out_h: int, out_w: int, rng: np.random.Generator
) -> np.ndarray:
    """Per-image random crop (the augmentation the reference's comment
    at ``cifar10cnn.py:67`` intended; enabled by ``DataConfig.random_crop``)."""
    n, h, w, _ = images.shape
    tops = rng.integers(0, h - out_h + 1, size=n)
    lefts = rng.integers(0, w - out_w + 1, size=n)
    # Gather windows via sliding-window view to stay vectorized.
    windows = np.lib.stride_tricks.sliding_window_view(
        images, (out_h, out_w), axis=(1, 2)
    )  # [N, h-out_h+1, w-out_w+1, C, out_h, out_w]
    out = windows[np.arange(n), tops, lefts]  # [N, C, out_h, out_w]
    return np.ascontiguousarray(out.transpose(0, 2, 3, 1))


def normalize(images: np.ndarray, mode: str) -> np.ndarray:
    """Pixel normalization (see ``DataConfig.normalize``). "standardize"
    matches ``tf.image.per_image_standardization``: per-image zero mean,
    divide by ``max(stddev, 1/sqrt(num_pixels))``."""
    if mode == "none":
        return images
    if mode == "scale":
        return images / np.float32(255.0)
    if mode == "standardize":
        n = np.float32(images[0].size)
        mean = images.mean(axis=(1, 2, 3), keepdims=True)
        std = images.std(axis=(1, 2, 3), keepdims=True)
        return (images - mean) / np.maximum(std, 1.0 / np.sqrt(n))
    raise ValueError(f"unknown normalize mode {mode!r}")


def random_brightness(images: np.ndarray, max_delta: float,
                      rng: np.random.Generator) -> np.ndarray:
    """Per-image additive brightness U[-max_delta, max_delta] (pixel
    units; ``tf.image.random_brightness`` semantics)."""
    deltas = rng.uniform(-max_delta, max_delta,
                         images.shape[0]).astype(np.float32)
    return images + deltas[:, None, None, None]


def random_contrast(images: np.ndarray, max_dev: float,
                    rng: np.random.Generator) -> np.ndarray:
    """Per-image contrast: scale deviation from the per-channel mean by
    U[1-max_dev, 1+max_dev] (``tf.image.random_contrast`` semantics —
    the mean is over H,W per channel)."""
    f = rng.uniform(1.0 - max_dev, 1.0 + max_dev,
                    images.shape[0]).astype(np.float32)
    mean = images.mean(axis=(1, 2), keepdims=True)
    return (images - mean) * f[:, None, None, None] + mean


def random_flip(images: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """Per-image horizontal flip with p=0.5."""
    flip = rng.random(images.shape[0]) < 0.5
    images = images.copy()
    images[flip] = images[flip, :, ::-1, :]
    return images
