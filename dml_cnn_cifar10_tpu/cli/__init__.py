"""Reference-compatible command line interface."""

from dml_cnn_cifar10_tpu.cli.main import main, build_parser  # noqa: F401
