"""CLI: keeps the reference's flags working, adds the framework's own.

Reference flags (``cifar10cnn.py:245-273``): ``--ps_hosts --worker_hosts
--job_name --task_index --data_dir --log_dir``. Mapping to the SPMD world:

- ``--job_name=ps`` — parameter servers don't exist under SPMD; the process
  prints a deprecation note and exits 0 so old 3-terminal launch scripts
  still "work" (the PS terminal just returns immediately).
- ``--worker_hosts`` + ``--task_index`` — become the ``jax.distributed``
  process set: ``num_processes=len(worker_hosts)``,
  ``process_id=task_index``, coordinator = first worker host.
- ``--ps_hosts`` — accepted and ignored (deprecation note).
- ``--data_dir`` — honored here. (The reference parses it but ignores it,
  using the hardcoded ``cifar10data`` — ``cifar10cnn.py:26`` vs ``:265-268``;
  we default to the same hardcoded value, honoring the flag when given.)
- ``--log_dir`` — checkpoint dir, as in the reference (``:222``).

New flags expose the config dataclasses (model/steps/batch/fidelity/mesh).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from dml_cnn_cifar10_tpu import config as config_lib


def _bool(v: str) -> bool:
    return v.lower() == "true"   # the reference's custom bool (:247)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dml_cnn_cifar10_tpu",
        description="TPU-native distributed CNN training "
                    "(reference-compatible CLI)")
    p.register("type", "bool", _bool)
    # --- reference flags (cifar10cnn.py:249-272) ---
    p.add_argument("--ps_hosts", type=str, default="",
                   help="DEPRECATED: comma-separated ps hosts (ignored; "
                        "SPMD has no parameter servers)")
    p.add_argument("--worker_hosts", type=str, default="",
                   help="Comma-separated hostname:port list; becomes the "
                        "jax.distributed process set")
    p.add_argument("--job_name", type=str, default="",
                   help="One of 'ps', 'worker' (ps exits immediately)")
    p.add_argument("--task_index", type=int, default=0,
                   help="Index of task within the job (process_id)")
    p.add_argument("--data_dir", type=str, default="cifar10data",
                   help="Directory for input data")
    p.add_argument("--log_dir", type=str, default="/tmp/train_logs",
                   help="Checkpoint/log directory")
    # --- framework flags ---
    p.add_argument("--model", type=str, default="cnn",
                   choices=["cnn", "resnet18", "resnet50", "vit_tiny",
                            "vit_moe"])
    p.add_argument("--dataset", type=str, default="cifar10",
                   choices=["cifar10", "cifar100", "synthetic",
                            "imagenet_synth"],
                   help="imagenet_synth: generated ImageNet-shaped shards "
                        "(256x256, 1000 classes, wide 2-byte labels) — the "
                        "ResNet-50 ladder rung on an air-gapped box")
    p.add_argument("--image_size", type=int, default=None,
                   help="stored square image side (default: 32, or 256 "
                        "for imagenet_synth)")
    p.add_argument("--crop_size", type=int, default=None,
                   help="model input side after crop (default: 24, or 224 "
                        "for imagenet_synth)")
    p.add_argument("--synthetic_train_records", type=int, default=None,
                   help="generated train records for "
                        "synthetic/imagenet_synth datasets")
    p.add_argument("--batch_size", type=int, default=128)
    p.add_argument("--total_steps", type=int, default=20000)
    p.add_argument("--output_every", type=int, default=200,
                   help="train-metrics cadence (reference OUTPUT_EVERY)")
    p.add_argument("--eval_every", type=int, default=500,
                   help="eval cadence (reference EVAL_EVERY)")
    p.add_argument("--checkpoint_every", type=int, default=1000)
    p.add_argument("--checkpoint_every_secs", type=float, default=None,
                   help="wall-clock checkpoint cadence in addition to the "
                        "step cadence (the reference's MTS saved every "
                        "600 s by default)")
    p.add_argument("--mode", type=str, default="train",
                   choices=["train", "eval", "export", "serve", "fleet",
                            "run"],
                   help="train; eval = restore latest checkpoint and sweep "
                        "the full test split; export = restore and write a "
                        "self-contained jax.export serving artifact; serve "
                        "= run the micro-batching inference engine over "
                        "the artifact (or latest checkpoint) behind an "
                        "HTTP endpoint; fleet = router + N replicated "
                        "serve workers with heartbeat liveness, "
                        "zero-downtime checkpoint hot-swap, and a "
                        "closed-loop autoscaler (docs/SERVING.md); run = "
                        "the unified multi-job runtime: one process, one "
                        "mesh, --jobs running concurrently, every "
                        "committed checkpoint hot-swapped into the "
                        "in-process serving head, alerts optionally "
                        "triggering fine-tune jobs (docs/RUNTIME.md)")
    p.add_argument("--export_path", type=str, default=None,
                   help="output file for --mode export "
                        "(default <log_dir>/model.jaxexport)")
    p.add_argument("--serve_artifact", type=str, default=None,
                   help="artifact to serve (--mode serve); default "
                        "<log_dir>/model.jaxexport when present, else "
                        "the latest checkpoint is restored and served "
                        "live")
    p.add_argument("--serve_buckets", type=str, default="1,8,32,128",
                   help="comma-separated pre-compiled batch sizes; a "
                        "request batch pads up to the smallest bucket "
                        "that fits (avoids per-shape recompiles)")
    p.add_argument("--serve_queue_depth", type=int, default=256,
                   help="admission control: submits beyond this queue "
                        "depth are shed immediately (HTTP 503) instead "
                        "of growing an unbounded backlog")
    p.add_argument("--serve_batch_window_ms", type=float, default=2.0,
                   help="max extra latency the batcher may wait to "
                        "coalesce a fuller batch")
    p.add_argument("--serve_deadline_ms", type=float, default=None,
                   help="per-request deadline; requests queued past it "
                        "are shed at dispatch (default: none)")
    p.add_argument("--serve_port", type=int, default=8000,
                   help="HTTP port for --mode serve (0 = ephemeral)")
    p.add_argument("--serve_metrics_every_s", type=float, default=5.0,
                   help="cadence of `serve` JSONL window records")
    p.add_argument("--serve_drain_deadline_s", type=float, default=5.0,
                   help="graceful-shutdown budget for --mode serve: on "
                        "SIGTERM/SIGINT stop accepting, let queued "
                        "batches finish for at most this long, shed the "
                        "rest, flush metrics, exit 0")
    p.add_argument("--serve_slo_ms", type=float, default=None,
                   help="p99 latency objective in ms; the fleet "
                        "autoscaler scales up while the replicas' p99 "
                        "sits above it (declarative elsewhere)")
    p.add_argument("--serve_quantize", type=str, default=None,
                   choices=["int8"],
                   help="quantized serving path (docs/QUANT.md): int8 "
                        "post-training quantization with calibrated "
                        "scales; served versions carry a '+int8' "
                        "suffix. Default: float serving")
    p.add_argument("--quant_calib_batches", type=int, default=4,
                   help="eval-stream batches the activation "
                        "calibration observes before quantizing")
    p.add_argument("--quant_max_delta", type=float, default=0.005,
                   help="pinned accuracy contract: max allowed "
                        "(float top-1 - int8 top-1) on the calibration "
                        "holdout, as a fraction (0.005 = 0.5%%); a "
                        "candidate beyond it is rejected at publish "
                        "time (quant_rejected) and float keeps serving")
    p.add_argument("--serve_cache_size", type=int, default=0,
                   help="exact-match response cache capacity (entries) "
                        "keyed by (input digest, version); hits bypass "
                        "the batcher; flushed on hot-swap. 0 = off")
    # --- unified runtime flags (--mode run; docs/RUNTIME.md) ---
    p.add_argument("--jobs", type=str, default="train,serve",
                   help="--mode run job spec: comma-separated from "
                        "{train, serve, eval}. train is a task job (the "
                        "runtime exits when task jobs drain); serve/eval "
                        "are service jobs stopped at drain. finetune "
                        "jobs are never listed — they are born from "
                        "alert triggers (--finetune_steps)")
    p.add_argument("--runtime_eval_every_s", type=float, default=2.0,
                   help="EvalJob cadence: seconds between accuracy "
                        "evaluations of the latest published weights")
    p.add_argument("--runtime_eval_batches", type=int, default=1,
                   help="test batches per EvalJob tick (each one "
                        "serving forward on the shared mesh)")
    p.add_argument("--runtime_serve_warmup", type="bool", default=False,
                   help="pre-compile the in-process serving head's "
                        "bucket programs at first publish (off keeps "
                        "the train path's fetch-parity invariant; the "
                        "request path compiles lazily)")
    p.add_argument("--finetune_steps", type=int, default=0,
                   help="alert→job control loop: an emitted alert "
                        "firing triggers a FineTuneJob continuing "
                        "training this many extra steps from the last "
                        "in-process train state. 0 = off")
    p.add_argument("--finetune_rules", type=str, default=None,
                   help="comma-separated alert rule names allowed to "
                        "trigger FineTuneJobs (default: any emitted "
                        "firing, --max_finetunes permitting)")
    p.add_argument("--max_finetunes", type=int, default=1,
                   help="lifetime budget of alert-triggered "
                        "FineTuneJobs per runtime")
    p.add_argument("--trace_sample_rate", type=float, default=0.0,
                   help="distributed request tracing: head-sample this "
                        "fraction of serving requests at the trace root "
                        "(client or first hop) and emit one `rspan` "
                        "JSONL record per hop; shed or retried requests "
                        "are always captured regardless of the rate "
                        "(docs/OBSERVABILITY.md Request-tracing)")
    p.add_argument("--fleet_min_replicas", type=int, default=2,
                   help="serving-fleet floor: the pool starts this many "
                        "workers and a fleet below it always scales "
                        "back up (self-healing after a worker death)")
    p.add_argument("--fleet_max_replicas", type=int, default=4,
                   help="serving-fleet ceiling for the autoscaler")
    p.add_argument("--fleet_port", type=int, default=8100,
                   help="router HTTP port for --mode fleet (0 = "
                        "ephemeral; workers always bind ephemeral ports "
                        "and advertise them via heartbeats)")
    p.add_argument("--fleet_dir", type=str, default=None,
                   help="fleet coordination directory (heartbeats, "
                        "published-version file, per-replica telemetry); "
                        "default <log_dir>/fleet. Shared filesystem in "
                        "production, a tmpdir in tests")
    p.add_argument("--fleet_autoscale", type="bool", default=True,
                   help="closed-loop autoscaling from the replicas' "
                        "serve JSONL windows (queue depth, shed "
                        "fraction, p99 vs --serve_slo_ms); false pins "
                        "the fleet at --fleet_min_replicas (deaths are "
                        "still replaced)")
    p.add_argument("--fleet_replica_dead_after_s", type=float,
                   default=3.0,
                   help="a worker whose newest heartbeat is older than "
                        "this is evicted from routing and its in-flight "
                        "requests re-routed to surviving replicas")
    p.add_argument("--fleet_publish", type="bool", default=False,
                   help="trainer-side hot-swap publish hook: every "
                        "committed checkpoint (with its integrity "
                        "sidecar) is published to the fleet dir so live "
                        "serve workers swap to it between micro-batches "
                        "(the online train-and-serve scenario)")
    p.add_argument("--cell", type=str, default="default",
                   help="comma-separated fleet cell names (failure "
                        "domains): replica i lands in cell i %% "
                        "len(cells) and advertises it per heartbeat; "
                        "the router prefers a request's X-DML-Cell "
                        "target (tools/loadgen.py --target_cell) and "
                        "fails over cross-cell — logged as cell_route "
                        "and force-traced — when the cell has no live "
                        "replica")
    p.add_argument("--learning_rate", type=float, default=0.1)
    p.add_argument("--fidelity", type=str, default="faithful",
                   choices=["faithful", "fixed"],
                   help="faithful reproduces the reference quirks (ReLU'd "
                        "logits, dead LR decay, single-batch eval, raw "
                        "pixels); fixed applies the sane versions")
    p.add_argument("--model_axis", type=int, default=1,
                   help="tensor-parallel mesh degree")
    p.add_argument("--seq_axis", type=int, default=1,
                   help="sequence-parallel mesh degree")
    p.add_argument("--sp_mode", type=str, default="ring",
                   choices=["ring", "ulysses"],
                   help="sequence-parallel attention strategy: ring "
                        "(K/V ppermute walk) or ulysses (seq<->head "
                        "all-to-all; needs heads %% seq_axis == 0)")
    p.add_argument("--pool", type=str, default=None,
                   choices=["cls", "mean"],
                   help="ViT head pooling; defaults to cls, or mean when "
                        "seq_axis > 1 (sequence sharding excludes a lone "
                        "cls token)")
    p.add_argument("--resnet_s2d", type="bool", default=False,
                   help="space-to-depth ResNet stem (ImageNet stems only): "
                        "4x4/1 conv on the 2x2-folded [112,112,12] input "
                        "instead of 7x7/2 on [224,224,3] - the MLPerf MXU-"
                        "occupancy trick; changes stem param shape")
    p.add_argument("--resnet_norm", type=str, default="bn",
                   choices=["bn", "nf"],
                   help="ResNet normalization: bn (reference semantics, "
                        "cross-replica BatchNorm) or nf (normalizer-free "
                        "byte-reduction rung: weight standardization + "
                        "SkipInit scalars, no stats passes; different "
                        "training semantics)")
    p.add_argument("--attn_window", type=int, default=None,
                   help="sliding-window (local) attention width for the "
                        "ViT family: band |row-col| < W on every path "
                        "(XLA, flash kernels, ring, ulysses); under ring "
                        "SP the window must fit one sequence shard")
    p.add_argument("--attn_causal", type="bool", default=False,
                   help="causal (autoregressive) attention mask in the "
                        "ViT family's transformer blocks")
    p.add_argument("--vit_heads", type=int, default=None,
                   help="ViT attention heads (default 3; ulysses sp needs "
                        "heads divisible by seq_axis)")
    p.add_argument("--vit_dim", type=int, default=None,
                   help="ViT embed dim (default 192)")
    p.add_argument("--vit_depth", type=int, default=None,
                   help="ViT blocks (default 12)")
    p.add_argument("--remat", type="bool", default=False,
                   help="recompute block activations in the backward pass "
                        "(ViT transformer blocks / ResNet residual "
                        "blocks; activation memory O(1) in depth)")
    p.add_argument("--pipe_axis", type=int, default=1,
                   help="pipeline-parallel mesh degree (stages; schedule "
                        "per --pipe_schedule)")
    p.add_argument("--pipe_schedule", type=str, default="1f1b",
                   choices=["1f1b", "1f1b_ring", "gpipe"],
                   help="pipeline schedule: 1f1b (no bubble compute, "
                        "recompute backward — minimal memory, measured "
                        "fastest), 1f1b_ring (2F+1B residual-ring "
                        "backward, opt-in) or gpipe (round-2 baseline)")
    p.add_argument("--pipe_microbatches", type=int, default=0,
                   help="pipeline microbatches per step (0 = one per "
                        "stage). More microbatches shrink 1f1b's live "
                        "activation footprint AND gpipe's bubble fraction "
                        "(M+P-1)/M at the cost of smaller per-microbatch "
                        "compute")
    p.add_argument("--moe_experts", type=int, default=0,
                   help="experts per MoE block (vit_moe); sharded over "
                        "the model axis (expert parallelism)")
    p.add_argument("--moe_top_k", type=int, default=1,
                   help="experts per token: 1 = Switch, 2 = GShard")
    p.add_argument("--moe_dispatch", type=str, default="einsum",
                   choices=["einsum", "scatter"],
                   help="MoE dispatch/combine: einsum ([T,E,C] one-hot "
                        "contractions, the ep-proven all-MXU path) or "
                        "scatter ((expert,slot) scatter/gather — O(T*D) "
                        "instead of O(T^2*f*D); fastest at long T on "
                        "one replica). Same semantics either way")
    p.add_argument("--resident_data", type="bool", default=True,
                   help="with --steps_per_dispatch >1, keep the uint8 "
                        "dataset in HBM and gather on device; multi-host "
                        "replicates the full split per process and ships "
                        "only index slices. The trainer auto-switches to "
                        "the NumPy pipeline for this path (the C++ "
                        "pool's bounded-shuffle stream has no index view)")
    p.add_argument("--device_index_stream", type="bool", default=True,
                   help="resident path only: generate the shuffled index "
                        "stream ON DEVICE inside the compiled chunk "
                        "(stateless per-epoch pseudo-permutation keyed on "
                        "the global step) — a training dispatch uploads "
                        "nothing and exact resume needs no sidecar. "
                        "Different (equally valid) permutation than the "
                        "host stream; toggling changes data order. "
                        "'false' restores the host numpy-PCG stream")
    p.add_argument("--use_native_loader", type="bool", default=True,
                   help="stream batches from the C++ bounded shuffle pool "
                        "(reference RandomShuffleQueue parity); false uses "
                        "the NumPy full-permutation pipeline")
    p.add_argument("--steps_per_dispatch", type=int, default=1,
                   help="train steps per device dispatch (lax.scan chunk; "
                        "output/eval/checkpoint cadences must be "
                        "multiples)")
    p.add_argument("--grad_accum", type=int, default=1,
                   help="microbatches per optimizer update (gradient "
                        "accumulation inside the compiled step)")
    p.add_argument("--explicit_collectives", type="bool", default=False,
                   help="use the shard_map+psum step instead of jit "
                        "auto-partitioning")
    p.add_argument("--fsdp", type="bool", default=False,
                   help="ZeRO/FSDP: shard params + optimizer moments over "
                        "the data axis (state memory 1/N; grads become "
                        "reduce-scatter)")
    p.add_argument("--optimizer_sharding", type=str, default="none",
                   choices=["none", "zero1"],
                   help="cross-replica weight-update sharding "
                        "(docs/SHARDING.md): zero1 allocates the "
                        "optimizer moments sharded 1/N over the data "
                        "axis from init on, reduce-scatters grads, "
                        "updates each replica's shard, and all-gathers "
                        "the new params for the next forward — same "
                        "math as replicated (pinned <=1e-6), "
                        "checkpoints interchange across modes. Needs "
                        "the GSPMD step; excludes --fsdp and "
                        "--async_staleness")
    p.add_argument("--fused_optimizer", type="bool", default=True,
                   help="fused single-pass SGD update (ops/optimizer.py: "
                        "momentum + weight decay + LR in one pass over "
                        "the param bytes; Pallas TPU kernel with an "
                        "identical-math XLA fallback by platform). "
                        "false restores the tree_map chain")
    p.add_argument("--partition_rules", type=str, default=None,
                   help="override the model's partition-rule table "
                        "(parallel/shardings.py engine; grammar in "
                        "docs/SHARDING.md): ordered ';'-separated "
                        "'regex=spec' rules matched against /-joined "
                        "param paths; spec is comma-separated per-dim "
                        "axis names, right-aligned ('-' = unsharded "
                        "dim, '^' prefix = left-aligned, empty = "
                        "replicated)")
    p.add_argument("--partition_rules_strict", type="bool", default=False,
                   help="error at build time on any param leaf no "
                        "partition rule matches (instead of silently "
                        "replicating it)")
    p.add_argument("--partition_report", type="bool", default=False,
                   help="print the which-rule-matched-which-param "
                        "report (path, shape, rule, spec) at Trainer "
                        "build")
    p.add_argument("--compute_dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "adamw", "lars", "lamb", "adafactor"],
                   help="sgd = reference; adamw for the transformer "
                        "ladder; lars/lamb add the per-layer trust ratio "
                        "for large-global-batch scaling; adafactor keeps "
                        "factored O(n+m) second moments (the memory "
                        "choice for large models)")
    p.add_argument("--momentum", type=float, default=0.0,
                   help="SGD momentum (reference uses plain SGD)")
    p.add_argument("--weight_decay", type=float, default=0.0)
    p.add_argument("--label_smoothing", type=float, default=0.0)
    p.add_argument("--random_brightness", type=float, default=0.0,
                   help="augment: per-image brightness delta (pixel "
                        "units; the TF tutorial used 63)")
    p.add_argument("--random_contrast", type=float, default=0.0,
                   help="augment: per-image contrast deviation (the TF "
                        "tutorial's [0.2,1.8] is 0.8)")
    p.add_argument("--grad_clip_norm", type=float, default=None,
                   help="global-norm gradient clipping")
    p.add_argument("--async_staleness", type=int, default=0,
                   help="emulate the reference's async-PS gradient "
                        "staleness deterministically: grads taken at a "
                        "snapshot S-1 updates old (0/1 = synchronous)")
    p.add_argument("--ema_decay", type=float, default=0.0,
                   help="parameter EMA decay for eval (0 = off; 0.999 "
                        "typical) — training optimizes raw params, eval "
                        "uses the average")
    p.add_argument("--schedule", type=str, default="exponential",
                   choices=["exponential", "cosine", "constant"],
                   help="LR schedule family (exponential = reference "
                        "parity; cosine for the ViT/ResNet ladder)")
    p.add_argument("--warmup_steps", type=int, default=0,
                   help="linear LR warmup prepended to any schedule")
    p.add_argument("--cosine_decay_steps", type=int, default=0,
                   help="cosine horizon (defaults to total_steps when "
                        "--schedule cosine and this is 0)")
    p.add_argument("--async_checkpoint", type="bool", default=False,
                   help="serialize+write checkpoints on a background "
                        "thread (training overlaps the disk IO)")
    p.add_argument("--ckpt_format", type=str, default="msgpack",
                   choices=["msgpack", "orbax", "sharded"],
                   help="checkpoint codec: single-file flax msgpack, the "
                        "orbax directory format, or per-process sharded "
                        "files (pod-scale: no full-state gather, each "
                        "process writes only its own shards; restore "
                        "auto-detects and is elastic across meshes)")
    p.add_argument("--shard_io_threads", type=int, default=4,
                   help="bounded thread pool for the sharded codec's "
                        "concurrent per-shard file IO: saves split the "
                        "local payload across up to this many part "
                        "files written in parallel, restores "
                        "read+verify+unpack shard files in parallel "
                        "(per-shard sha256 sidecars; shard_io JSONL "
                        "telemetry). 1 = fully serial, same bytes")
    p.add_argument("--check_numerics", type="bool", default=False,
                   help="halt at the next metrics boundary on non-finite "
                        "loss without checkpointing the poisoned state "
                        "(faithful parity runs NaN by design — keep off)")
    p.add_argument("--on_nonfinite", type=str, default="halt",
                   choices=["halt", "skip", "rollback"],
                   help="what a --check_numerics detection does: halt "
                        "raises without saving; skip discards the "
                        "updates since the last finite boundary and "
                        "keeps training; rollback raises a classified "
                        "failure the --supervise loop answers by "
                        "restoring the last good checkpoint (optionally "
                        "scaling LR by --rollback_lr_scale). skip/"
                        "rollback degrade to halt when the "
                        "--recovery_retries budget is exhausted "
                        "(docs/RESILIENCE.md)")
    p.add_argument("--supervise", type="bool", default=False,
                   help="wrap training in the recovery supervisor: "
                        "classified recoverable failures (non-finite "
                        "loss under rollback, data-pipeline errors, "
                        "checkpoint-restore errors) restore the last "
                        "verifiable checkpoint, rewind the exact-resume "
                        "data state, back off, and resume")
    p.add_argument("--recovery_retries", type=int, default=3,
                   help="shared recovery budget: max on_nonfinite=skip "
                        "events per run AND max supervisor restarts; "
                        "exhausted degrades to halt")
    p.add_argument("--retry_budget_window", type=int, default=0,
                   help="progress-based retry-budget reset: when > 0, "
                        "the supervisor's attempt counter resets after "
                        "the newest checkpoint advances this many "
                        "steps past the last retry — long runs "
                        "absorbing well-spaced faults keep recovering "
                        "while a fault burst still degrades to halt. "
                        "0 = lifetime budget (historical behavior)")
    p.add_argument("--recovery_backoff_s", type=float, default=0.5,
                   help="supervisor restart backoff base (doubles per "
                        "attempt, capped at 30s)")
    p.add_argument("--rollback_lr_scale", type=float, default=1.0,
                   help="LR multiplier applied at each supervisor "
                        "rollback of a non-finite failure (1.0 = keep "
                        "LR; a deterministically diverging run replayed "
                        "at the same LR diverges again)")
    p.add_argument("--fault_spec", type=str, default=None,
                   help="deterministic fault injection for recovery "
                        "drills: comma-separated kind@trigger with "
                        "kinds nan, ckpt_corrupt, sigterm, data_stall "
                        "— plus the cluster kinds heartbeat_stall, "
                        "host_lost, collective_hang, host_return, "
                        "decision_corrupt (need --cluster_dir). A "
                        "trigger is a global step (fires once at the "
                        "first dispatch at/after it; several faults "
                        "may share a step) or a recovery phase "
                        "restore|adopt|decide that fires inside the "
                        "supervisor's recovery paths (utils/faults.py; "
                        "tools/chaos.py fuzzes these). The network "
                        "kinds net_partition, net_delay, net_drop, "
                        "net_dup (need --cluster_transport net) arm a "
                        "deterministic fault on the coordination "
                        "service isolating the injecting process "
                        "(utils/netfaults.py)")
    p.add_argument("--cluster_dir", type=str, default=None,
                   help="shared directory arming the cluster-resilience "
                        "layer (parallel/cluster.py): per-process "
                        "heartbeats, a collective watchdog classifying "
                        "straggler vs. hang/host-loss at each dispatch "
                        "seam, and chief-recorded coordinated elastic "
                        "restarts (docs/RESILIENCE.md). NFS/GCS-fuse in "
                        "production, a tmpdir in the CPU simulation")
    p.add_argument("--heartbeat_interval_s", type=float, default=0.5,
                   help="background heartbeat cadence; beats publish "
                        "from a daemon thread so a compiling/blocked "
                        "host still looks alive")
    p.add_argument("--straggler_after_s", type=float, default=2.0,
                   help="dispatch-seam overrun after which the watchdog "
                        "classifies peers (straggler telemetry for "
                        "beating-but-behind peers)")
    p.add_argument("--peer_dead_after_s", type=float, default=10.0,
                   help="a peer whose newest heartbeat is older than "
                        "this is declared lost: the run aborts "
                        "deterministically (and elastically restarts "
                        "under --supervise) instead of blocking in an "
                        "XLA collective forever")
    p.add_argument("--collective_timeout_s", type=float, default=120.0,
                   help="armed-seam duration after which the watchdog "
                        "presumes the main thread wedged inside a "
                        "collective and aborts this process after "
                        "logging (a loud corpse beats a silent hang)")
    p.add_argument("--min_hosts", type=int, default=1,
                   help="floor for coordinated elastic restarts: the "
                        "chief halts instead of shrinking the world "
                        "below this many surviving hosts")
    p.add_argument("--elastic_expand", type="bool", default=False,
                   help="elastic scale-UP: a returning (or brand-new) "
                        "host announces itself with a rejoin-phase "
                        "heartbeat instead of staying fenced; the chief "
                        "records a monotone-epoch expand decision "
                        "growing the world to the live hosts and every "
                        "process re-enters restore at the larger size "
                        "(docs/RESILIENCE.md). false = shrink-only: "
                        "evicted hosts stay fenced")
    p.add_argument("--peer_redundancy", type="bool", default=False,
                   help="diskless recovery (ckpt/peerstore.py): at every "
                        "checkpoint boundary each host also pushes its "
                        "local shard payload to its ring-successor's "
                        "replica store under --cluster_dir (async, "
                        "off the step path, sha256 sidecars); on "
                        "host_lost the chief may decide source=peer and "
                        "survivors restore with ZERO checkpoint reads, "
                        "reconstructing the lost host's shards from its "
                        "replica; any missing/stale/corrupt replica "
                        "falls back to the disk restore walk. n=1: "
                        "no-op (flag legal)")
    p.add_argument("--replica_keep", type=int, default=2,
                   help="peer-replica retention: committed replica "
                        "payloads kept per owner (newest K checkpoint "
                        "boundaries)")
    p.add_argument("--restore_deadline_s", type=float, default=0.0,
                   help="wall-clock budget for the newest→oldest "
                        "checkpoint fallback walk at restore; exceeding "
                        "it raises a classified ckpt_restore error "
                        "instead of scanning a huge retention dir "
                        "forever (0 = unbounded)")
    p.add_argument("--cluster_transport", type=str, default="file",
                   choices=["file", "net"],
                   help="coordination transport (heartbeats, restart "
                        "decisions, peer-replica pushes, fleet "
                        "discovery): 'file' = the shared-directory "
                        "store (n=1 and test fallback); 'net' = a "
                        "socket service (parallel/net.py) hosted by "
                        "process 0 (the fleet controller in --mode "
                        "fleet) over the same directory — bounded "
                        "timeouts, classified transport errors, and "
                        "the seam the net_* chaos faults partition "
                        "(docs/RESILIENCE.md Transport selection)")
    p.add_argument("--net_timeout_s", type=float, default=5.0,
                   help="per-request socket timeout on the net "
                        "coordination transport; every operation is "
                        "bounded so a dead/partitioned coordinator "
                        "degrades to the classified peer_lost/eviction "
                        "paths, never a hang (lockstep sims run 0.5)")
    p.add_argument("--net_retries", type=int, default=2,
                   help="bounded retry budget per net-transport "
                        "operation (exponential backoff between "
                        "attempts; retried on timeout/unreachable/5xx)")
    p.add_argument("--cluster_lockstep", type="bool", default=False,
                   help="simulation only: make the dispatch seam a "
                        "software barrier over the heartbeat store so "
                        "multi-process CPU runs without real "
                        "collectives still block on (and recover from) "
                        "a lost peer; real pods leave this off")
    p.add_argument("--coordinator_timeout_s", type=float, default=60.0,
                   help="per-attempt jax.distributed.initialize wait "
                        "for the coordinator; a slow-to-start "
                        "coordinator is retried with bounded backoff "
                        "(--coordinator_retries), not crashed on")
    p.add_argument("--coordinator_retries", type=int, default=3,
                   help="bounded retry budget around the coordinator "
                        "bootstrap")
    p.add_argument("--preempt_sync_every", type=int, default=10,
                   help="steps between multi-host preemption/clock-save "
                        "agreement allgathers (single-process reacts "
                        "immediately)")
    p.add_argument("--compile_cache_dir", type=str, default=None,
                   help="persistent compilation cache directory "
                        "(compilecache/, docs/COMPILECACHE.md): compiled "
                        "programs keyed by fingerprint persist here and "
                        "warm restarts — supervisor recovery, elastic "
                        "re-entry, serve warmup — skip the XLA recompile "
                        "(jax's native persistent cache is armed under "
                        "DIR/xla; executable deserialization is opt-in "
                        "per backend via DML_COMPILECACHE_EXEC_BACKENDS). "
                        "Fail-open; emits `compile` JSONL events")
    p.add_argument("--compile_cache_max_bytes", type=int,
                   default=2_000_000_000,
                   help="LRU size bound for --compile_cache_dir "
                        "(least-recently-used entries are evicted after "
                        "each store)")
    p.add_argument("--peak_tflops", type=float, default=None,
                   help="per-chip peak TFLOP/s; enables the MFU metric "
                        "in the jsonl stream")
    p.add_argument("--metrics_jsonl", type=str, default=None)
    p.add_argument("--stats_port", type=int, default=0,
                   help="live metrics export: serve GET /metrics "
                        "(Prometheus text exposition of the "
                        "process-local counter/gauge/histogram "
                        "registry) plus /healthz from a lightweight "
                        "stats-HTTP thread while the trainer runs. "
                        "0 = off. --mode serve and the fleet router "
                        "expose /metrics on their existing servers "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--alert_rules", type=str, default=None,
                   help="custom streaming alert rules layered over the "
                        "built-in defaults (goodput collapse, "
                        "host-bound drain, nonfinite/recovery bursts, "
                        "heartbeat staleness, shed>1%%, p99 vs "
                        "--serve_slo_ms, HBM headroom): ';'-separated "
                        "name=expr[@window][!severity] with expr "
                        "'kind.field OP value' (threshold on "
                        "consecutive records), "
                        "'rate(kind[.field=value])>=N' (trailing "
                        "step/'Ns' second window), or 'absent(kind)' "
                        "(@Ns). Firing emits rate-limited alert/"
                        "alert_resolved JSONL records "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--autopilot", type="bool", default=False,
                   help="alert-driven remediation: attach the autopilot "
                        "policy engine to the alert trigger seam and "
                        "answer qualifying alert firings with gated "
                        "remediation actions (rollback with "
                        "--rollback_lr_scale, memory shrink + recompile "
                        "through the compile cache, fleet scale-up + "
                        "tier shed, raising --replica_keep), each "
                        "emitting a `remediation` JSONL record linked "
                        "to the firing alert's id and postmortem "
                        "bundle (docs/AUTOPILOT.md)")
    p.add_argument("--autopilot_policies", type=str, default=None,
                   help="replace the built-in autopilot policy table: "
                        "';'-separated 'name=pattern[|pattern...]"
                        "->action[:k=v,...][@cooldown[s]]' where "
                        "pattern fnmatches alert rule names, action is "
                        "rollback | shrink_memory | scale_up_shed | "
                        "raise_replica_keep, and @N is a step cooldown "
                        "(@Ns seconds). Default: nonfinite_burst->"
                        "rollback, hbm_headroom->shrink_memory, "
                        "serve/fleet SLO+shed->scale_up_shed, "
                        "peer_churn->raise_replica_keep "
                        "(docs/AUTOPILOT.md)")
    p.add_argument("--autopilot_budget", type=int, default=8,
                   help="global remediation budget shared by all "
                        "autopilot policies (the --max_finetunes "
                        "pattern generalized): once spent, further "
                        "qualifying firings get explicit "
                        "suppressed_budget records and the plain alert "
                        "stands")
    p.add_argument("--postmortem_dir", type=str, default=None,
                   help="arm the alert-triggered flight recorder: keep "
                        "a bounded in-memory ring of the last "
                        "--flightrec_size metrics records and, when a "
                        "streaming alert fires, write an atomic "
                        "post-mortem bundle (ring + alert + config + "
                        "env + live context) under this directory — one "
                        "bundle per alert firing. Render with "
                        "tools/postmortem.py (docs/OBSERVABILITY.md)")
    p.add_argument("--flightrec_size", type=int, default=256,
                   help="flight-recorder ring capacity in records "
                        "(per process; needs --postmortem_dir)")
    p.add_argument("--telemetry", type="bool", default=False,
                   help="run-health telemetry: host-loop span tracing, "
                        "goodput fractions, and HBM snapshots emitted "
                        "into the metrics JSONL at the existing "
                        "boundaries (zero extra device fetches; see "
                        "docs/OBSERVABILITY.md)")
    p.add_argument("--trace_events_path", type=str, default=None,
                   help="write the host-loop spans as a Chrome "
                        "trace-event JSON file (Perfetto-loadable next "
                        "to the --profile_dir XLA trace); needs "
                        "--telemetry true")
    p.add_argument("--health_metrics", type="bool", default=False,
                   help="compile global grad-norm / param-norm / "
                        "update-ratio scalars into the train step; they "
                        "ride the fused boundary fetch into the train "
                        "JSONL records (no extra round trips)")
    p.add_argument("--tensorboard_dir", type=str, default=None,
                   help="write TensorBoard event files (chief only; the "
                        "reference's MTS wrote summaries to --log_dir)")
    p.add_argument("--profile_dir", type=str, default=None)
    p.add_argument("--profile_at_steps", type=str, default=None,
                   help="device-time attribution window 'N:K': capture "
                        "a programmatic jax.profiler trace from global "
                        "step N for K steps (closing at the next "
                        "drained metrics boundary), parse it host-side, "
                        "and emit per-op `devtime` JSONL records "
                        "(top-k ops; compute/collective/infeed "
                        "buckets). Writes under --profile_dir when "
                        "set, else <log_dir>/devprof "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--seed", type=int, default=0)
    return p


def config_from_args(args: argparse.Namespace) -> config_lib.TrainConfig:
    make = (config_lib.reference_config if args.fidelity == "faithful"
            else config_lib.fixed_config)
    cfg = make(
        batch_size=args.batch_size,
        total_steps=args.total_steps,
        output_every=args.output_every,
        eval_every=args.eval_every,
        checkpoint_every=args.checkpoint_every,
        checkpoint_every_secs=args.checkpoint_every_secs,
        log_dir=args.log_dir,
        metrics_jsonl=args.metrics_jsonl,
        stats_port=args.stats_port,
        alert_rules=args.alert_rules,
        telemetry=args.telemetry,
        trace_events_path=args.trace_events_path,
        health_metrics=args.health_metrics,
        peak_tflops=args.peak_tflops,
        preempt_sync_every=args.preempt_sync_every,
        check_numerics=args.check_numerics,
        on_nonfinite=args.on_nonfinite,
        supervise=args.supervise,
        recovery_retries=args.recovery_retries,
        retry_budget_window=args.retry_budget_window,
        recovery_backoff_s=args.recovery_backoff_s,
        rollback_lr_scale=args.rollback_lr_scale,
        fault_spec=args.fault_spec,
        compile_cache_dir=args.compile_cache_dir,
        compile_cache_max_bytes=args.compile_cache_max_bytes,
        ckpt_format=args.ckpt_format,
        tensorboard_dir=args.tensorboard_dir,
        profile_dir=args.profile_dir,
        profile_at_steps=args.profile_at_steps,
        seed=args.seed,
    )
    cfg.data.dataset = args.dataset
    cfg.data.data_dir = args.data_dir
    cfg.data.random_brightness = args.random_brightness
    cfg.data.random_contrast = args.random_contrast
    if args.dataset == "cifar100":
        cfg.data.num_classes = cfg.model.num_classes = 100
    if args.dataset == "imagenet_synth":
        # The ResNet-50 ImageNet-1k rung (BASELINE.json configs[3]):
        # canonical 256-stored / 224-crop geometry, 1000 classes.
        cfg.data.image_height = cfg.data.image_width = 256
        cfg.data.crop_height = cfg.data.crop_width = 224
        cfg.data.num_classes = cfg.model.num_classes = 1000
    if args.image_size is not None:
        cfg.data.image_height = cfg.data.image_width = args.image_size
    if args.crop_size is not None:
        cfg.data.crop_height = cfg.data.crop_width = args.crop_size
    if args.synthetic_train_records is not None:
        cfg.data.synthetic_train_records = args.synthetic_train_records
    cfg.model.name = args.model
    cfg.model.compute_dtype = args.compute_dtype
    cfg.optim.learning_rate = args.learning_rate
    cfg.optim.grad_accum = args.grad_accum
    cfg.optim.optimizer = args.optimizer
    cfg.optim.momentum = args.momentum
    cfg.optim.weight_decay = args.weight_decay
    cfg.optim.label_smoothing = args.label_smoothing
    cfg.optim.grad_clip_norm = args.grad_clip_norm
    cfg.optim.ema_decay = args.ema_decay
    cfg.optim.async_staleness = args.async_staleness
    cfg.optim.schedule = args.schedule
    cfg.optim.warmup_steps = args.warmup_steps
    cfg.optim.cosine_decay_steps = args.cosine_decay_steps
    if args.schedule == "cosine" and not args.cosine_decay_steps:
        cfg.optim.cosine_decay_steps = cfg.total_steps
    cfg.steps_per_dispatch = args.steps_per_dispatch
    cfg.resident_data = args.resident_data
    cfg.data.device_index_stream = args.device_index_stream
    cfg.data.use_native_loader = args.use_native_loader
    # Seed the data stream (shuffle + device-side augmentation draws) from
    # the run seed too — otherwise --seed would not vary augmentation.
    cfg.data.seed = args.seed
    cfg.async_checkpoint = args.async_checkpoint
    cfg.model.sp_mode = args.sp_mode
    cfg.model.attn_window = args.attn_window
    cfg.model.attn_causal = args.attn_causal
    cfg.model.resnet_s2d = args.resnet_s2d
    cfg.model.resnet_norm = args.resnet_norm
    if args.pool is not None:
        cfg.model.pool = args.pool
    elif args.seq_axis > 1:
        cfg.model.pool = "mean"
    for f in ("vit_heads", "vit_dim", "vit_depth"):
        if getattr(args, f) is not None:
            setattr(cfg.model, f, getattr(args, f))
    cfg.parallel.model_axis = args.model_axis
    cfg.parallel.seq_axis = args.seq_axis
    cfg.parallel.pipe_axis = args.pipe_axis
    cfg.parallel.cluster_dir = args.cluster_dir
    cfg.parallel.heartbeat_interval_s = args.heartbeat_interval_s
    cfg.parallel.straggler_after_s = args.straggler_after_s
    cfg.parallel.peer_dead_after_s = args.peer_dead_after_s
    cfg.parallel.collective_timeout_s = args.collective_timeout_s
    cfg.parallel.min_hosts = args.min_hosts
    cfg.parallel.elastic_expand = args.elastic_expand
    cfg.parallel.peer_redundancy = args.peer_redundancy
    cfg.parallel.replica_keep = args.replica_keep
    cfg.restore_deadline_s = args.restore_deadline_s
    cfg.parallel.cluster_transport = args.cluster_transport
    cfg.parallel.net_timeout_s = args.net_timeout_s
    cfg.parallel.net_retries = args.net_retries
    cfg.parallel.cluster_lockstep = args.cluster_lockstep
    cfg.shard_io_threads = args.shard_io_threads
    cfg.parallel.coordinator_timeout_s = args.coordinator_timeout_s
    cfg.parallel.coordinator_retries = args.coordinator_retries
    if args.pipe_microbatches and args.pipe_axis <= 1:
        # Silently measuring "plain dp" while believing it's an M=4P
        # schedule is exactly the trap the moe_experts guard below
        # already closes for its flag pair.
        raise SystemExit(
            f"--pipe_microbatches={args.pipe_microbatches} requires "
            f"--pipe_axis > 1 (got {args.pipe_axis}); without a pipe "
            f"axis there is no schedule to microbatch")
    if args.pipe_schedule != "1f1b" and args.pipe_axis <= 1:
        # Mirror the --pipe_microbatches guard: without a pipe axis the
        # sequential fast path runs and a requested gpipe schedule would
        # be silently ignored — reject instead of mislabeling a bench.
        raise SystemExit(
            f"--pipe_schedule={args.pipe_schedule} requires --pipe_axis "
            f"> 1 (got {args.pipe_axis}); without a pipe axis there is "
            f"no schedule to select")
    cfg.model.pipe_microbatches = args.pipe_microbatches
    cfg.model.pipe_schedule = args.pipe_schedule
    if args.moe_experts and args.model != "vit_moe":
        raise SystemExit(
            f"--moe_experts requires --model vit_moe (got {args.model})")
    cfg.model.moe_experts = args.moe_experts
    if args.model == "vit_moe" and args.moe_experts == 0:
        cfg.model.moe_experts = 8
    cfg.model.moe_top_k = args.moe_top_k
    cfg.model.moe_dispatch = args.moe_dispatch
    cfg.model.remat = args.remat
    cfg.parallel.explicit_collectives = args.explicit_collectives
    cfg.parallel.fsdp = args.fsdp
    if args.fsdp and args.explicit_collectives:
        raise SystemExit("--fsdp needs the GSPMD (default) step, not "
                         "--explicit_collectives")
    cfg.optim.optimizer_sharding = args.optimizer_sharding
    cfg.optim.fused_optimizer = args.fused_optimizer
    cfg.parallel.partition_rules = args.partition_rules
    cfg.parallel.partition_rules_strict = args.partition_rules_strict
    cfg.parallel.partition_report = args.partition_report
    if args.optimizer_sharding == "zero1":
        # Mirror the builder-level checks with CLI-shaped errors (the
        # same trap the --fsdp guard above closes): a silently ignored
        # sharding mode would mislabel every bench that rides it.
        if args.fsdp:
            raise SystemExit(
                "--optimizer_sharding zero1 does not compose with "
                "--fsdp (ZeRO-3 already shards the optimizer moments)")
        if args.explicit_collectives:
            raise SystemExit(
                "--optimizer_sharding zero1 needs the GSPMD (default) "
                "step, not --explicit_collectives")
    if args.alert_rules:
        # Fail a typo'd rule at flag-parse time with a CLI-shaped
        # error — a rule that silently never fires is the worst
        # failure mode an alerting layer can have.
        from dml_cnn_cifar10_tpu.utils.alerts import parse_alert_rules
        try:
            parse_alert_rules(args.alert_rules)
        except ValueError as e:
            raise SystemExit(f"--alert_rules: {e}")
    try:
        cfg.serve.buckets = tuple(
            int(b) for b in args.serve_buckets.split(",") if b.strip())
    except ValueError:
        raise SystemExit(
            f"--serve_buckets must be comma-separated ints, got "
            f"{args.serve_buckets!r}")
    cfg.serve.max_queue_depth = args.serve_queue_depth
    cfg.serve.batch_window_ms = args.serve_batch_window_ms
    cfg.serve.deadline_ms = args.serve_deadline_ms
    cfg.serve.port = args.serve_port
    cfg.serve.artifact_path = args.serve_artifact
    cfg.serve.metrics_every_s = args.serve_metrics_every_s
    cfg.serve.drain_deadline_s = args.serve_drain_deadline_s
    cfg.serve.slo_ms = args.serve_slo_ms
    cfg.serve.trace_sample_rate = args.trace_sample_rate
    cfg.serve.quantize = args.serve_quantize
    cfg.serve.quant_calib_batches = args.quant_calib_batches
    cfg.serve.quant_max_delta = args.quant_max_delta
    cfg.serve.cache_size = args.serve_cache_size
    cfg.postmortem_dir = args.postmortem_dir
    cfg.flightrec_size = args.flightrec_size
    cfg.autopilot.enabled = args.autopilot
    cfg.autopilot.policies = args.autopilot_policies
    cfg.autopilot.budget = args.autopilot_budget
    if args.autopilot_policies:
        # Same policy as the --alert_rules pre-parse above: a typo'd
        # policy that silently never remediates must fail the run at
        # flag-parse time.
        from dml_cnn_cifar10_tpu.autopilot import parse_policies
        try:
            parse_policies(args.autopilot_policies)
        except ValueError as e:
            raise SystemExit(f"--autopilot_policies: {e}")
    cfg.runtime.jobs = args.jobs
    cfg.runtime.eval_every_s = args.runtime_eval_every_s
    cfg.runtime.eval_batches = args.runtime_eval_batches
    cfg.runtime.serve_warmup = args.runtime_serve_warmup
    cfg.runtime.finetune_steps = args.finetune_steps
    cfg.runtime.finetune_rules = args.finetune_rules
    cfg.runtime.max_finetunes = args.max_finetunes
    if args.mode == "run":
        # Fail a typo'd job spec at flag-parse time, CLI-shaped — same
        # policy as the --alert_rules pre-parse above.
        from dml_cnn_cifar10_tpu.runtime.jobs import parse_jobs
        try:
            parse_jobs(args.jobs)
        except ValueError as e:
            raise SystemExit(f"--jobs: {e}")
    if args.fleet_min_replicas < 1 \
            or args.fleet_max_replicas < args.fleet_min_replicas:
        raise SystemExit(
            f"--fleet_min_replicas/--fleet_max_replicas must satisfy "
            f"1 <= min <= max, got {args.fleet_min_replicas}/"
            f"{args.fleet_max_replicas}")
    cfg.fleet.min_replicas = args.fleet_min_replicas
    cfg.fleet.max_replicas = args.fleet_max_replicas
    cfg.fleet.port = args.fleet_port
    cfg.fleet.dir = args.fleet_dir
    cfg.fleet.autoscale = args.fleet_autoscale
    cfg.fleet.replica_dead_after_s = args.fleet_replica_dead_after_s
    cfg.fleet.publish = args.fleet_publish
    cfg.fleet.cell = args.cell
    # The worker set also names the cluster-resilience world: process_id
    # feeds chiefness (multihost.is_chief) and the heartbeat identity
    # even when jax.distributed never initializes (the lockstep CPU
    # simulation runs one independent JAX world per process).
    workers = [h for h in args.worker_hosts.split(",") if h]
    if len(workers) > 1:
        cfg.parallel.coordinator_address = workers[0]
        cfg.parallel.num_processes = len(workers)
    cfg.parallel.process_id = args.task_index
    return cfg


def main(argv: Optional[List[str]] = None) -> int:
    args, unparsed = build_parser().parse_known_args(argv)
    if unparsed:
        print(f"[cli] ignoring unrecognized args: {unparsed}",
              file=sys.stderr)

    # Before ANY jax backend use: the native persistent compilation
    # cache (the warm start for backends where executable swapping is
    # off — the default) is read at client creation; arming it later is
    # a silent no-op.
    from dml_cnn_cifar10_tpu.compilecache import arm_native_cache
    arm_native_cache(args.compile_cache_dir)

    if args.job_name == "ps":
        # The reference blocks a whole process on server.join()
        # (cifar10cnn.py:191-192). SPMD has no parameter servers: parameters
        # live replicated/sharded in device HBM and gradients all-reduce
        # over ICI. The ps role exits successfully for launch-script compat.
        print("[cli] job_name=ps is obsolete under SPMD: parameters live on "
              "device, gradients all-reduce over ICI. Nothing to serve; "
              "exiting.")
        return 0

    workers = [h for h in args.worker_hosts.split(",") if h]
    if len(workers) > 1 and not args.cluster_lockstep:
        # Lockstep-simulation runs keep one independent JAX world per
        # process (the cluster layer, not XLA, provides the barrier) —
        # everything else bootstraps the real distributed runtime.
        from dml_cnn_cifar10_tpu.parallel import multihost
        multihost.initialize_from_hosts(workers, args.task_index)

    cfg = config_from_args(args)
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    if args.mode == "eval":
        import jax

        from dml_cnn_cifar10_tpu.data import pipeline as pipe
        cfg.eval_full_test_set = True
        trainer = Trainer(cfg, task_index=args.task_index)
        state = trainer.init_or_restore()
        step = int(jax.device_get(state.step))
        if step == 0:
            print(f"[cli] warning: no checkpoint under {cfg.log_dir}; "
                  "evaluating fresh-initialized weights", file=sys.stderr)
        # Per-process shard of the split, like fit(): each process feeds
        # only its slice into the collective sweep — an unsharded pipeline
        # would count every record process_count times.
        num_shards = jax.process_count()
        shard = jax.process_index()
        test_it = pipe.input_pipeline(
            cfg.data, cfg.batch_size // num_shards, train=False,
            seed=cfg.seed + shard, shard=shard, num_shards=num_shards)
        acc = trainer.evaluate(state, test_it)
        print(f" --- Test Accuracy = {acc * 100:.2f}%.")
        print(f"[cli] eval at step {step}: {acc * 100:.2f}% on "
              f"{test_it.total_records} records")
        return 0

    if args.mode == "export":
        import os

        import jax

        from dml_cnn_cifar10_tpu import export as export_lib
        trainer = Trainer(cfg, task_index=args.task_index)
        state = trainer.init_or_restore()
        step = int(jax.device_get(state.step))
        if step == 0:
            print(f"[cli] warning: no checkpoint under {cfg.log_dir}; "
                  "exporting fresh-initialized weights", file=sys.stderr)
        path = args.export_path or f"{cfg.log_dir}/model.jaxexport"
        # The host fetch inside export_forward is a collective when state
        # is sharded multi-host: every process participates, the chief
        # writes.
        # Export the EMA weights (and EMA BN stats) when the optimizer
        # tracks them — the same weights eval mode scores.
        params = state.opt.get("ema", state.params)
        mstate = state.opt.get("ema_mstate", state.model_state) \
            if trainer.model_def.has_state else None
        if cfg.serve.quantize == "int8":
            # Quantized export: calibrate on the eval stream, then bake
            # the int8 weights + scales into the artifact. Default
            # output name advertises the path (model_int8.jaxexport).
            # import from the module path: the package re-exports a
            # `calibrate` FUNCTION that shadows the module name
            from dml_cnn_cifar10_tpu.quant.calibrate import (
                calibrate as quant_calibrate, calibration_sets)
            calib, _, _ = calibration_sets(
                cfg.data, 64, cfg.serve.quant_calib_batches, holdout=0)
            scales = quant_calibrate(
                params, calib, cfg.model, cfg.data, batch_size=64,
                num_batches=cfg.serve.quant_calib_batches)
            if not args.export_path:
                path = f"{cfg.log_dir}/model_int8.jaxexport"
            blob = export_lib.export_quantized_forward(
                cfg.model, cfg.data, params, scales)
        else:
            blob = export_lib.export_forward(
                trainer.model_def, cfg.model, cfg.data, params, mstate)
        if jax.process_index() == 0:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            export_lib.save_exported(path, blob)
            kind = "int8 " if cfg.serve.quantize == "int8" else ""
            print(f"[cli] exported step-{step} {kind}forward "
                  f"({len(blob)} bytes, tpu+cpu, symbolic batch) to {path}")
        return 0

    if args.mode == "serve":
        from dml_cnn_cifar10_tpu.serve.server import main_serve
        return main_serve(cfg, task_index=args.task_index)

    if args.mode == "fleet":
        from dml_cnn_cifar10_tpu.fleet.controller import main_fleet
        return main_fleet(cfg)

    if args.mode == "run":
        from dml_cnn_cifar10_tpu.runtime import main_run
        return main_run(cfg, task_index=args.task_index)

    if cfg.supervise:
        from dml_cnn_cifar10_tpu.train.supervisor import fit_supervised
        result = fit_supervised(cfg, task_index=args.task_index)
        if result is None:
            # Fenced by a cluster restart decision (peers declared this
            # process dead): a clean, saveless exit is the contract.
            print("[cli] fenced by the cluster restart decision; "
                  "exiting cleanly")
            return 0
    else:
        result = Trainer(cfg, task_index=args.task_index).fit()
    print(f"[cli] done at step {result.final_step}; "
          f"{result.images_per_sec:.1f} images/sec")
    return 0


if __name__ == "__main__":
    sys.exit(main())
