"""``--mode fleet``: the process topology, owned end to end.

One controller process runs the router (in-process threads), the
checkpoint publisher, and the autoscaler loop, and owns a pool of serve
worker SUBPROCESSES::

      trainer ──ckpt──▶ log_dir ──▶ DirectoryPublisher ─▶ published.json
                                                              │ poll
        client ─▶ Router (:fleet_port) ──proxy──▶ worker 0 ◀──┤ swap
                    ▲  beats (fleet_dir)          worker 1 ◀──┘
                    └──────────────────────────── worker N

Workers are real processes, not threads, deliberately: a replica must
be killable (the failure unit), retirable (SIGTERM → drain), and
spawnable (the scale unit) without touching the others — the same
reason the cluster layer's simulation runs one process per host. Each
worker gets the fleet's exact config as a JSON file
(``config_to_dict``), binds an ephemeral port, and announces itself by
heartbeat; nothing here tracks ports.

The autoscaler loop closes the control loop: aggregate the replicas'
serve JSONL windows + heartbeat queue depths → ``decide`` (pure,
``fleet/autoscaler.py``) → spawn or retire, within
``--fleet_min/max_replicas``, one action per cooldown. Every decision
that acts logs a ``scale`` JSONL record.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from dml_cnn_cifar10_tpu.fleet import autoscaler as autoscaler_lib
from dml_cnn_cifar10_tpu.fleet.publisher import (DirectoryPublisher,
                                                 fleet_coord_dir)
from dml_cnn_cifar10_tpu.fleet.router import Router


class WorkerPool:
    """Spawn/retire/reap the worker subprocesses. Replica ids are
    never reused — eviction state, heartbeat files, and telemetry
    streams all key on them."""

    def __init__(self, config_path: str, fleet_dir: str,
                 worker_fault: Optional[str] = None):
        self.config_path = config_path
        self.fleet_dir = fleet_dir
        self.worker_fault = worker_fault   # "<rid>:<kind>@<n>" drill hook
        self.procs: Dict[int, subprocess.Popen] = {}
        self.retiring: Dict[int, subprocess.Popen] = {}
        self.next_id = 0

    def _fault_for(self, replica_id: int) -> Optional[str]:
        if not self.worker_fault:
            return None
        rid, sep, spec = self.worker_fault.partition(":")
        if sep and rid.isdigit() and int(rid) == replica_id:
            return spec
        return None

    def spawn(self) -> int:
        replica_id = self.next_id
        self.next_id += 1
        argv = [sys.executable, "-m", "dml_cnn_cifar10_tpu.fleet.worker",
                self.config_path, str(replica_id)]
        fault = self._fault_for(replica_id)
        if fault:
            argv.append(fault)
        log_path = os.path.join(self.fleet_dir, "telemetry",
                                f"replica_{replica_id}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        # Workers inherit the environment; their stdout/stderr go to a
        # per-replica log, not the router's console. The platform pin
        # rides a dedicated var because some hosts' sitecustomize
        # overwrites JAX_PLATFORMS at interpreter startup — the worker
        # entry re-asserts it after that (fleet/worker.py __main__).
        env = dict(os.environ)
        if env.get("JAX_PLATFORMS"):
            env["DML_FLEET_WORKER_PLATFORM"] = env["JAX_PLATFORMS"]
        import dml_cnn_cifar10_tpu
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(dml_cnn_cifar10_tpu.__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        with open(log_path, "ab") as logf:
            self.procs[replica_id] = subprocess.Popen(
                argv, stdout=logf, stderr=subprocess.STDOUT, env=env)
        print(f"[fleet] spawned replica {replica_id} "
              f"(pid {self.procs[replica_id].pid})")
        return replica_id

    def retire(self, replica_id: int) -> None:
        """Graceful retirement: SIGTERM → the worker's PreemptionGuard
        drain. The process is reaped (not waited on) by the next
        :meth:`reap` pass so retirement never blocks the control
        loop."""
        proc = self.procs.pop(replica_id, None)
        if proc is None:
            return
        self.retiring[replica_id] = proc
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        print(f"[fleet] retiring replica {replica_id} (SIGTERM)")

    def reap(self) -> Dict[int, int]:
        """Collect exits; returns {replica_id: returncode} of newly
        dead workers still counted as active (crashes — retirements
        exit through ``retiring`` silently)."""
        dead = {}
        for rid, proc in list(self.procs.items()):
            rc = proc.poll()
            if rc is not None:
                dead[rid] = rc
                del self.procs[rid]
        for rid, proc in list(self.retiring.items()):
            if proc.poll() is not None:
                del self.retiring[rid]
        return dead

    def active_ids(self):
        return sorted(self.procs)

    def terminate_all(self, timeout_s: float = 10.0) -> None:
        for rid in list(self.procs):
            self.retire(rid)
        deadline = time.time() + timeout_s
        for rid, proc in list(self.retiring.items()):
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()


class FleetController:
    """Router + publisher + pool + the autoscaler control loop."""

    def __init__(self, cfg, logger=None):
        self.cfg = cfg
        self.fleet_dir = fleet_coord_dir(cfg)
        os.makedirs(self.fleet_dir, exist_ok=True)
        self.logger = logger
        # Streaming alerts over the controller's own stream (fleet
        # windows, scale events, evictions via peer_lost) — and the
        # autoscaler's extra input: active load-shaped alerts push
        # scale-up, ANY active alert vetoes scale-down. Evaluated once
        # per control tick, the fleet's metrics boundary.
        from dml_cnn_cifar10_tpu.utils import alerts as alerts_lib
        self.alerts = alerts_lib.AlertEngine.from_config(cfg)
        if self.alerts is not None and logger is not None:
            logger.add_observer(self.alerts.observer(logger))
        # Alert-driven remediation (--autopilot; autopilot/engine.py):
        # a qualifying SLO/shed alert requests an immediate scale-up —
        # served at the NEXT tick ahead of the autoscaler's own cadence
        # and cooldown (the autoscaler would get there too, one
        # autoscale_every_s later; the autopilot buys back that lag and
        # leaves the remediation lineage in the JSONL stream).
        from dml_cnn_cifar10_tpu.autopilot.engine import AutopilotEngine
        self._scale_up_requested: Optional[str] = None
        self.autopilot = AutopilotEngine.from_config(
            cfg, logger=logger)
        if self.autopilot is not None:
            self.autopilot.bind("scale_up", self._request_scale_up)
            if self.alerts is not None:
                self.autopilot.attach(self.alerts)
        # NET coordination transport (--cluster_transport net): the
        # controller hosts the fleet's coordination service over the
        # fleet dir; workers beat through CoordClient. The router keeps
        # reading the SAME directory straight off disk (it is
        # co-process with the server), so discovery needs no extra hop.
        self.net_server = None
        if getattr(cfg.parallel, "cluster_transport", "file") == "net":
            from dml_cnn_cifar10_tpu.parallel import net as net_lib
            self.net_server = net_lib.CoordServer(self.fleet_dir)
        self.router = Router(
            self.fleet_dir,
            dead_after_s=cfg.fleet.replica_dead_after_s,
            route_retries=cfg.fleet.route_retries,
            route_timeout_s=cfg.fleet.route_timeout_s,
            route_backoff_s=cfg.fleet.route_backoff_s,
            logger=logger,
            trace_sample_rate=cfg.serve.trace_sample_rate)
        config_path = os.path.join(self.fleet_dir, "worker_config.json")
        from dml_cnn_cifar10_tpu.config import config_to_dict
        worker_cfg = config_to_dict(cfg)
        # Workers must never fight over one HTTP port or one JSONL
        # stream: ephemeral ports, per-replica telemetry (worker.py
        # derives the path from fleet dir + replica id).
        worker_cfg["serve"]["port"] = 0
        worker_cfg["metrics_jsonl"] = None
        worker_cfg["fleet"]["dir"] = self.fleet_dir
        tmp = config_path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(worker_cfg, f, indent=1)
        os.replace(tmp, config_path)
        self.pool = WorkerPool(config_path, self.fleet_dir,
                               worker_fault=cfg.fleet.worker_fault)
        self.publisher = DirectoryPublisher(
            cfg.log_dir, self.fleet_dir,
            poll_s=cfg.fleet.publish_poll_s, logger=logger,
            quantize=cfg.serve.quantize)
        self._cooldown_until = 0.0
        self._last_decide = 0.0
        self._last_fleet_emit = time.time()

    def _request_scale_up(self, rule_name: str) -> None:
        """Autopilot scale_up seam: remember the request; :meth:`tick`
        serves it ahead of the autoscaler cadence."""
        self._scale_up_requested = rule_name

    # -- the control loop body (one tick, also driven by tests) --------

    def signals(self) -> autoscaler_lib.FleetSignals:
        live = self.router.live()
        live_ids = {v.replica_id for v in live}
        starting = len([rid for rid in self.pool.active_ids()
                        if rid not in live_ids])
        return autoscaler_lib.aggregate_signals(
            live, starting, os.path.join(self.fleet_dir, "telemetry"))

    def tick(self) -> None:
        """Reap crashes, then (cooldown permitting) one scale action."""
        dead = self.pool.reap()
        for rid, rc in dead.items():
            # A crashed worker stops beating and the router evicts it
            # on staleness; evicting here too closes the gap between
            # process exit and beat expiry.
            self.router.evict(rid, f"replica_evicted_exit_{rc}")
        now = time.time()
        if now - self._last_fleet_emit >= self.cfg.fleet.metrics_every_s:
            self._last_fleet_emit = now
            self.router.emit()
            if self.alerts is not None:
                self.alerts.evaluate(
                    emit=self.logger.log if self.logger is not None
                    else None)
        requested, self._scale_up_requested = \
            self._scale_up_requested, None
        if requested is not None \
                and len(self.pool.active_ids()) \
                < self.cfg.fleet.max_replicas:
            # Autopilot remediation: spawn now, ahead of the decide
            # cadence; the scale record keeps the autoscaler's shape
            # with an autopilot-attributed reason.
            self.pool.spawn()
            self._cooldown_until = now + self.cfg.fleet.scale_cooldown_s
            if self.logger is not None:
                self.logger.log("scale", action="up",
                                reason=f"autopilot:{requested}",
                                replicas=len(self.pool.active_ids()))
            print(f"[fleet] scale up (autopilot:{requested}): "
                  f"{len(self.pool.active_ids())} worker(s)")
            return
        if now < self._cooldown_until \
                or now - self._last_decide < self.cfg.fleet.autoscale_every_s:
            return
        self._last_decide = now
        sig = self.signals()
        decision = autoscaler_lib.decide(
            sig, self.cfg.fleet.min_replicas,
            self.cfg.fleet.max_replicas,
            slo_ms=self.cfg.serve.slo_ms,
            scale_up_queue_depth=self.cfg.fleet.scale_up_queue_depth,
            alerts_active=(self.alerts.active_names()
                           if self.alerts is not None else ()))
        if decision.action == "hold":
            return
        if not self.cfg.fleet.autoscale and decision.reason != "below_min":
            # Autoscaling off still self-heals: a fleet below its floor
            # is a missing replica, not a capacity opinion.
            return
        if decision.action == "up":
            self.pool.spawn()
        elif decision.action == "down":
            victim = max((v.replica_id for v in self.router.live()),
                         default=None)
            if victim is None:
                return
            self.router.drain_replica(victim)
            self.pool.retire(victim)
        self._cooldown_until = now + self.cfg.fleet.scale_cooldown_s
        if self.logger is not None:
            self.logger.log(
                "scale", action=decision.action, reason=decision.reason,
                replicas=len(self.pool.active_ids()))
        print(f"[fleet] scale {decision.action} ({decision.reason}): "
              f"{len(self.pool.active_ids())} worker(s)")

    # -- lifecycle ------------------------------------------------------

    def start(self) -> int:
        """Spawn the floor, start publisher + router; returns the
        router's bound port."""
        self.publisher.scan_once()   # publish what already exists
        self.publisher.start()
        for _ in range(self.cfg.fleet.min_replicas):
            self.pool.spawn()
        server = self.router.serve(self.cfg.fleet.port)
        return server.server_address[1]

    def shutdown(self) -> None:
        self.publisher.stop()
        self.router.emit(final=True)
        self.router.shutdown()
        self.pool.terminate_all()
        # Last: workers drain first so their final beats don't land on
        # a closed coordination service.
        if self.net_server is not None:
            self.net_server.stop()


def main_fleet(cfg, ready_event: Optional[threading.Event] = None,
               stop_event: Optional[threading.Event] = None) -> int:
    """Blocking fleet loop with graceful SIGTERM/SIGINT shutdown:
    retire every worker (their own drains bound the wait), final
    ``fleet_done`` record, exit 0."""
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
    from dml_cnn_cifar10_tpu.utils.preemption import PreemptionGuard

    logger = MetricsLogger(jsonl_path=cfg.metrics_jsonl)
    controller = FleetController(cfg, logger=logger)
    port = controller.start()
    print(f"[fleet] router listening on :{port} "
          f"(POST /predict, GET /stats, GET /healthz); "
          f"{cfg.fleet.min_replicas} worker(s) warming up; "
          f"fleet dir {controller.fleet_dir}")
    try:
        with PreemptionGuard() as guard:
            if ready_event is not None:
                ready_event.set()
            try:
                while not guard.requested and (
                        stop_event is None or not stop_event.is_set()):
                    controller.tick()
                    time.sleep(0.1)
                why = (f"signal {guard.signum}" if guard.requested
                       else "stop requested")
            except KeyboardInterrupt:
                why = "keyboard interrupt"
            print(f"[fleet] {why}: retiring workers")
    finally:
        controller.shutdown()
        logger.flush()
        logger.close()
    print("[fleet] exiting cleanly")
    return 0
