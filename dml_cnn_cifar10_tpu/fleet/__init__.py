"""The serving fleet: replicated workers behind a router, with
zero-downtime checkpoint hot-swap and closed-loop autoscaling.

``serve/`` is one engine in one process; this package is the layer
that makes it a deployment (``--mode fleet``, docs/SERVING.md):

- ``controller.py`` — ``main_fleet``: owns the worker subprocess pool,
  the router threads, the checkpoint publisher, and the autoscaler
  control loop.
- ``router.py`` — heartbeat-discovered membership, least-queue-depth
  placement, eviction + in-flight re-route on worker death.
- ``worker.py`` — one serve replica: engine + batcher + HTTP plus
  heartbeats and the hot-swap watcher.
- ``publisher.py`` — which checkpoint version the fleet serves
  (integrity-sidecar-gated, atomic, monotone).
- ``autoscaler.py`` — the pure decision table over the replicas' own
  serve JSONL metrics.

The ingredients are deliberately reused, not reinvented:
``parallel/cluster.py`` heartbeats carry the fleet's liveness (the
beat payload generalized to ``extra``), PR-3 integrity sidecars gate
what is publishable, and the PR-5 compile cache is what makes replica
spin-up cheap enough for an autoscaler to be worth closing the loop.
"""

from dml_cnn_cifar10_tpu.fleet.autoscaler import (FleetSignals,  # noqa: F401
                                                  ScaleDecision, decide)
from dml_cnn_cifar10_tpu.fleet.publisher import (  # noqa: F401
    DirectoryPublisher, PublishedVersion, publish_checkpoint,
    read_published)
from dml_cnn_cifar10_tpu.fleet.router import (ReplicaView,  # noqa: F401
                                              Router, live_views,
                                              pick_replica)
