"""Checkpoint publishing: which weights version the fleet should serve.

One small piece of shared truth, same rules as every other piece in
this repo (checkpoints, restart decisions): a JSON file committed by
atomic rename with a monotone sequence number, pollable by any number
of readers without locks.

Two producers write it:

- the **trainer-side hook** (``--fleet_publish``,
  ``train/loop.py`` → :func:`publish_checkpoint`) — publishes each
  checkpoint the moment its integrity sidecar commits, the online
  train-and-serve path;
- the **directory publisher** (:class:`DirectoryPublisher`, started by
  the fleet controller) — polls the checkpoint dir so checkpoints
  dropped there by anything else (a separate trainer, a copy from
  another cluster) get published too.

Both gate on the PR-3 integrity sidecars, and STRICTER than restore
does: restore tolerates a missing sidecar (pre-integrity checkpoints
must stay restorable), but publishing one would hand every serve
worker a version it cannot verify — so no sidecar means not
publishable. A checkpoint that fails verification is skipped (and
remembered, so the watcher does not re-hash it every poll).

Workers poll :func:`read_published` (``fleet/worker.py``) and hot-swap
when ``seq`` advances; the version string is the checkpoint step.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional

from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib

PUBLISHED_FILE = "published.json"


def fleet_coord_dir(cfg) -> str:
    """The fleet's shared coordination directory (heartbeats, the
    published-version file, per-replica telemetry): ``cfg.fleet.dir``
    or ``<log_dir>/fleet``."""
    return cfg.fleet.dir or os.path.join(cfg.log_dir, "fleet")


@dataclasses.dataclass
class PublishedVersion:
    seq: int          # monotone publish counter (swap trigger)
    version: str      # the tag responses will carry (checkpoint step,
                      # "+int8"-suffixed for a quantized variant)
    step: int
    path: str         # the checkpoint to restore
    published_at: float
    # Quantized variant marker (docs/QUANT.md): "int8" tells workers to
    # calibrate + convert the restored float checkpoint and run the
    # accuracy-delta gate before swapping. Defaulted so published.json
    # files from float-only fleets keep reading back fine.
    quantize: Optional[str] = None


def read_published(fleet_dir: str) -> Optional[PublishedVersion]:
    """Latest published version, or None (no publish yet; torn reads
    self-heal on the next poll, like heartbeats)."""
    try:
        with open(os.path.join(fleet_dir, PUBLISHED_FILE)) as f:
            return PublishedVersion(**json.load(f))
    except (OSError, ValueError, TypeError):
        return None


def publishable(path: str) -> tuple:
    """(ok, reason) — stricter than restore's verify: the sidecar must
    EXIST and match. See the module docstring for why."""
    if not os.path.exists(ckpt_lib.checksum_path(path)):
        return False, "no integrity sidecar"
    return ckpt_lib.verify_checkpoint(path)


def publish_checkpoint(fleet_dir: str, ckpt_path: str, step: int,
                       logger=None,
                       quantize: Optional[str] = None
                       ) -> Optional[PublishedVersion]:
    """Gate on the integrity sidecar, then commit ``published.json``
    (atomic rename, monotone seq). Returns the published record, or
    None when the candidate was rejected or is not newer than what is
    already published.

    ``quantize="int8"`` publishes the QUANTIZED variant of the same
    checkpoint: the path still names the float weights (workers
    calibrate/convert on adoption, behind the accuracy gate) but the
    version string carries the ``+int8`` suffix, so every response the
    fleet returns advertises the numeric path that computed it."""
    ok, reason = publishable(ckpt_path)
    if not ok:
        print(f"[fleet] NOT publishing {ckpt_path}: {reason}")
        return None
    prior = read_published(fleet_dir)
    if prior is not None and prior.step >= step:
        return None
    version = str(step)
    if quantize == "int8":
        from dml_cnn_cifar10_tpu.quant.convert import quantized_version
        version = quantized_version(version)
    rec = PublishedVersion(
        seq=(prior.seq + 1) if prior is not None else 1,
        version=version, step=int(step), path=os.path.abspath(ckpt_path),
        published_at=time.time(), quantize=quantize)
    os.makedirs(fleet_dir, exist_ok=True)
    target = os.path.join(fleet_dir, PUBLISHED_FILE)
    tmp = target + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(dataclasses.asdict(rec), f)
    os.replace(tmp, target)
    if logger is not None:
        logger.log("fleet_publish", seq=rec.seq, version=rec.version,
                   step=rec.step, path=rec.path)
    print(f"[fleet] published version {rec.version} (seq {rec.seq}): "
          f"{ckpt_path}")
    return rec


class DirectoryPublisher(threading.Thread):
    """Watch a checkpoint dir; publish each new verifiable checkpoint.

    Polling, not inotify: the checkpoint dir may be NFS/GCS-fuse where
    file-event APIs don't exist — the same reasoning as the heartbeat
    store.
    Checkpoints that fail the publish gate are remembered per (step,
    mtime) so a corrupt file is not re-hashed every poll but a repaired
    one (re-copied with a fresh sidecar) is re-considered.
    """

    def __init__(self, ckpt_dir: str, fleet_dir: str,
                 poll_s: float = 0.5, logger=None,
                 quantize: Optional[str] = None):
        super().__init__(name="fleet-publisher", daemon=True)
        self.ckpt_dir = ckpt_dir
        self.fleet_dir = fleet_dir
        self.poll_s = poll_s
        self.logger = logger
        self.quantize = quantize
        self._stop = threading.Event()
        self._rejected = set()   # (step, sidecar_mtime) seen-bad cache

    def stop(self) -> None:
        self._stop.set()

    def scan_once(self) -> Optional[PublishedVersion]:
        """One watch pass: publish the newest publishable checkpoint
        beyond the current published step (also called directly by
        tests — the poll loop is just this on a timer)."""
        prior = read_published(self.fleet_dir)
        floor = prior.step if prior is not None else -1
        steps = sorted(ckpt_lib.all_checkpoint_steps(self.ckpt_dir),
                       reverse=True)
        for step in steps:
            if step <= floor:
                break
            path = ckpt_lib.checkpoint_path_at_step(self.ckpt_dir, step)
            if path is None:
                continue
            sidecar = ckpt_lib.checksum_path(path)
            try:
                key = (step, os.path.getmtime(sidecar))
            except OSError:
                key = (step, None)
            if key in self._rejected:
                continue
            rec = publish_checkpoint(self.fleet_dir, path, step,
                                     logger=self.logger,
                                     quantize=self.quantize)
            if rec is not None:
                return rec
            self._rejected.add(key)
        return None

    def run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.scan_once()
            except Exception as e:   # keep watching; a bad pass is not fatal
                print(f"[fleet] publisher scan error: {e!r}")
