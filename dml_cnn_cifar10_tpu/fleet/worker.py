"""One serve replica of the fleet: engine + batcher + HTTP, plus the
three fleet duties a lone ``--mode serve`` process doesn't have:

1. **Advertise** — publish heartbeats to the fleet dir
   (``HeartbeatStore`` beats with ``extra = {replica_id, version,
   queue_depth, port}``; ``step`` is the completed-request counter).
   Phase ``warmup`` until the HTTP socket is up and every bucket is
   compiled, then ``serve`` — the router only routes to ``serve``.
2. **Hot-swap** — poll the published-version file
   (``fleet/publisher.py``); when ``seq`` advances, restore exactly the
   published checkpoint (integrity-verified,
   ``ckpt.restore_checkpoint_at``) and
   :meth:`~dml_cnn_cifar10_tpu.serve.engine.ServingEngine.try_swap` it
   in between micro-batches. A candidate that fails restore or the
   engine's shape/dtype contract is rejected (``swap_rejected`` JSONL)
   and the old version keeps serving.
3. **Die loudly or drain cleanly** — SIGTERM takes the same
   PreemptionGuard drain as ``--mode serve`` (the autoscaler retires
   replicas with SIGTERM); the ``--worker_fault`` drill hook arms a
   ``utils/faults.py`` kind (``host_lost`` = ``os._exit``, no cleanup)
   after N batch dispatches so the router's evict/re-route path is
   testable on CPU in tier-1.

Spawned by the fleet controller as ``python -m
dml_cnn_cifar10_tpu.fleet.worker <config.json> <replica_id> [fault]``;
its telemetry stream is ``<fleet_dir>/telemetry/replica_<id>.jsonl``
(serve windows, compile events, swap events) — the same files the
autoscaler reads its signals from.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional

from dml_cnn_cifar10_tpu.fleet import publisher as publisher_lib
from dml_cnn_cifar10_tpu.parallel.cluster import HeartbeatStore
from dml_cnn_cifar10_tpu.serve.batcher import MicroBatcher
from dml_cnn_cifar10_tpu.serve.cache import ResponseCache
from dml_cnn_cifar10_tpu.serve.metrics import ServeMetrics
from dml_cnn_cifar10_tpu.serve.server import _make_handler, _MetricsFlusher


def replica_jsonl_path(fleet_dir: str, replica_id: int) -> str:
    return os.path.join(fleet_dir, "telemetry",
                        f"replica_{replica_id}.jsonl")


class _FaultingEngine:
    """Engine proxy arming one ``utils/faults.py`` kind at the Nth
    TRAFFIC dispatch (warmup forwards go through the real engine and
    don't count). The fleet analogue of the trainer's ``--fault_spec``
    seam — how tier-1 kills a worker mid-load without mocking."""

    def __init__(self, engine, kind: str, at_n: int, on_stall=None):
        self._engine = engine
        self._kind = kind
        self._at_n = int(at_n)
        self._n = 0
        self._fired = False
        self._on_stall = on_stall

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def forward_timed_versioned(self, batch):
        self._n += 1
        if not self._fired and self._n >= self._at_n:
            self._fired = True
            from dml_cnn_cifar10_tpu.utils.faults import EXIT_HOST_LOST
            print(f"[fleet] replica fault {self._kind} at dispatch "
                  f"{self._n}", flush=True)
            if self._kind == "host_lost":
                os._exit(EXIT_HOST_LOST)
            elif self._kind == "heartbeat_stall" \
                    and self._on_stall is not None:
                self._on_stall()
        return self._engine.forward_timed_versioned(batch)


def _parse_fault(fault: Optional[str]):
    """``"kind@n"`` with kind in {host_lost, heartbeat_stall}."""
    if not fault:
        return None
    kind, sep, n = fault.partition("@")
    if not sep or kind not in ("host_lost", "heartbeat_stall"):
        raise ValueError(f"bad worker fault {fault!r}: want "
                         f"host_lost@N or heartbeat_stall@N")
    return kind, int(n)


class _SwapWatcher(threading.Thread):
    """Poll the published-version file; restore + try_swap on advance.

    The restore target is the worker's own TrainState (structure from
    its first restore), so a published checkpoint from a DIFFERENT
    model config fails restore — which is handled exactly like an
    engine-contract mismatch: ``swap_rejected``, keep serving.

    A record carrying ``quantize="int8"`` is adopted through the quant
    publish gate instead (``quant/convert.gate_and_swap``): recalibrate
    for the restored weights, score int8 vs float top-1 on the holdout,
    and swap only on pass — a failing candidate emits
    ``quant_rejected`` and the current version keeps serving."""

    def __init__(self, fleet_dir: str, engine, trainer, state,
                 poll_s: float, last_seq: int, logger=None,
                 quant_ctx=None):
        super().__init__(name="fleet-swap-watcher", daemon=True)
        self.fleet_dir = fleet_dir
        self.engine = engine
        self.trainer = trainer
        self.state = state
        self.poll_s = poll_s
        self.last_seq = last_seq
        self.logger = logger
        self.quant_ctx = quant_ctx
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def check_once(self) -> bool:
        """One poll; True when a swap was installed."""
        rec = publisher_lib.read_published(self.fleet_dir)
        if rec is None or rec.seq <= self.last_seq:
            return False
        # Whatever happens below, this seq is handled: a bad candidate
        # must not be retried every poll_s forever.
        self.last_seq = rec.seq
        from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
        try:
            new_state = ckpt_lib.restore_checkpoint_at(rec.path,
                                                       self.state)
        except Exception as e:
            if self.logger is not None:
                self.logger.log("swap_rejected",
                                replica_id=self.engine.replica_id,
                                version=rec.version,
                                reason=f"restore failed: {e}")
            print(f"[fleet] REJECTED published version {rec.version}: "
                  f"restore failed ({e})")
            return False
        self.state = new_state
        params = new_state.opt.get("ema", new_state.params)
        mstate = new_state.opt.get("ema_mstate", new_state.model_state) \
            if self.trainer.model_def.has_state else None
        if getattr(rec, "quantize", None) == "int8":
            if self.quant_ctx is None:
                if self.logger is not None:
                    self.logger.log("swap_rejected",
                                    replica_id=self.engine.replica_id,
                                    version=rec.version,
                                    reason="quantized publish but worker "
                                           "has no int8 program "
                                           "(--serve_quantize unset)")
                print(f"[fleet] REJECTED published version "
                      f"{rec.version}: worker has no int8 program")
                return False
            from dml_cnn_cifar10_tpu.quant.convert import gate_and_swap
            ok, _ = gate_and_swap(self.engine, self.quant_ctx, params,
                                  str(rec.step), logger=self.logger)
            return ok
        ok, _ = self.engine.try_swap(params, mstate, version=rec.version)
        return ok

    def run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception as e:
                print(f"[fleet] swap watcher error: {e!r}")


class _BeatPublisher(threading.Thread):
    """Advertise this replica: liveness + placement signals per beat."""

    def __init__(self, store: HeartbeatStore, batcher, engine,
                 interval_s: float, port_ref: dict, phase_ref: dict,
                 cell: str = "default"):
        super().__init__(name="fleet-beat-publisher", daemon=True)
        self.store = store
        self.batcher = batcher
        self.engine = engine
        self.interval_s = interval_s
        self.port_ref = port_ref
        self.phase_ref = phase_ref
        self.cell = cell
        self._stop = threading.Event()
        self._stalled = False

    def stall(self) -> None:
        """Fault hook: stop beating while serving continues — from the
        router's side, indistinguishable from a dead worker."""
        self._stalled = True

    def stop(self) -> None:
        self._stop.set()

    def beat_once(self) -> None:
        if self._stalled:
            return
        self.store.publish(
            self.batcher.metrics.cumulative()["completed"],
            self.phase_ref["phase"],
            extra={"replica_id": self.store.process_id,
                   "version": self.engine.version,
                   "queue_depth": self.batcher.queue_depth(),
                   # Device-time attribution for the fleet: the router/
                   # autoscaler (and trace_aggregate's request-flow
                   # view) can tell a slow DEVICE from a deep queue.
                   "device_ms": self.batcher.metrics.recent_device_ms(),
                   # Failure domain (--cell): the router prefers a
                   # request's target cell and logs the crossing when
                   # it must fail over out of it.
                   "cell": self.cell,
                   "port": self.port_ref.get("port")})

    def run(self) -> None:
        self.beat_once()
        while not self._stop.wait(self.interval_s):
            self.beat_once()


def main_worker(cfg, replica_id: int, fault: Optional[str] = None,
                ready_event: Optional[threading.Event] = None,
                stop_event: Optional[threading.Event] = None) -> int:
    """Blocking worker loop (the fleet's ``main_serve`` analogue)."""
    from dml_cnn_cifar10_tpu.utils.logging import MetricsLogger
    from dml_cnn_cifar10_tpu.utils.preemption import PreemptionGuard

    fleet_dir = publisher_lib.fleet_coord_dir(cfg)
    jsonl = replica_jsonl_path(fleet_dir, replica_id)
    os.makedirs(os.path.dirname(jsonl), exist_ok=True)
    # The replica's whole stream — serve windows, compile events, swap
    # events, and anything the Trainer-based restore logs — goes to one
    # per-replica file; the autoscaler and telemetry_report read these.
    cfg.metrics_jsonl = jsonl
    logger = MetricsLogger(jsonl_path=jsonl, task_index=replica_id)
    # Per-replica streaming alerts (shed / p99-vs-SLO / custom rules):
    # same engine the lone --mode serve path arms, emitting into this
    # replica's stream — which the controller's signal aggregation and
    # the live monitor already tail.
    from dml_cnn_cifar10_tpu.utils import alerts as alerts_lib
    from dml_cnn_cifar10_tpu.utils.flightrec import FlightRecorder
    # Flight recorder first (observers run in attach order — the record
    # that trips an alert must be ringed before the capture fires); the
    # engine doesn't exist yet, so context goes through a holder.
    holder: dict = {}
    flightrec = FlightRecorder.from_config(
        cfg, context_fn=lambda: {
            "active_version": getattr(holder.get("engine"), "version",
                                      None),
            "replica_id": replica_id},
        logger=logger)
    if flightrec is not None:
        logger.add_observer(flightrec.observer())
    alert_engine = alerts_lib.AlertEngine.from_config(cfg)
    if alert_engine is not None:
        logger.add_observer(alert_engine.observer(logger))

    # Engine over the PUBLISHED version when there is one (every
    # replica of a fleet must serve the same weights regardless of
    # spawn order), else the latest checkpoint — structure restored
    # through the Trainer exactly like --mode serve, so fleet outputs
    # pin bit-equal to the single-process path.
    import jax

    from dml_cnn_cifar10_tpu.ckpt import checkpoint as ckpt_lib
    from dml_cnn_cifar10_tpu.serve.engine import ServingEngine
    from dml_cnn_cifar10_tpu.train.loop import Trainer

    trainer = Trainer(cfg, task_index=replica_id)
    state = trainer.init_or_restore()
    published = publisher_lib.read_published(fleet_dir)
    last_seq = 0
    if published is not None:
        if int(jax.device_get(state.step)) != published.step:
            state = ckpt_lib.restore_checkpoint_at(published.path, state)
        last_seq = published.seq
    version = str(int(jax.device_get(state.step)))
    params = state.opt.get("ema", state.params)
    mstate = state.opt.get("ema_mstate", state.model_state) \
        if trainer.model_def.has_state else None
    engine = ServingEngine.from_params(
        trainer.model_def, cfg.model, cfg.data, params, mstate,
        compile_cache=trainer.compile_cache, logger=logger,
        version=version, replica_id=replica_id)
    holder["engine"] = engine

    # Quantized serving (docs/QUANT.md): the engine stays FLOAT-first —
    # it is built over the float weights, then armed with the int8
    # program so try_swap can route either tree shape. Adoption follows
    # the PUBLISHED record: a replica joining a fleet whose current
    # version is quantized gates + swaps before going routable (every
    # replica serves the same variant regardless of spawn order); with
    # nothing quantized published yet it serves float and the watcher
    # gates the first quantized publish like any other. A failed gate
    # means float keeps serving and the version string says so — that
    # is the contract.
    quant_ctx = None
    if cfg.serve.quantize == "int8":
        from dml_cnn_cifar10_tpu.quant.convert import (QuantContext,
                                                       gate_and_swap)
        quant_ctx = QuantContext.build(trainer.model_def, cfg.model,
                                       cfg.data, cfg.serve)
        engine.attach_program(
            "int8", quant_ctx.quant_fn,
            (quant_ctx.quantize(params), None),
            warm_buckets=cfg.serve.buckets)
        if published is not None and \
                getattr(published, "quantize", None) == "int8":
            gate_and_swap(engine, quant_ctx, params, version,
                          logger=logger)

    # Advertise on the fleet's coordination transport. NET mode talks
    # to the controller-hosted CoordServer (parallel/net.py) — bounded
    # timeouts, classified errors, the chaos partition seam; a beat the
    # transport loses is just a beat the router never sees, the same
    # silence a crashed worker produces. FILE mode stays the n=1/test
    # fallback.
    if getattr(cfg.parallel, "cluster_transport", "file") == "net":
        from dml_cnn_cifar10_tpu.parallel import net as net_lib
        net_client = net_lib.CoordClient(
            fleet_dir, replica_id,
            timeout_s=cfg.parallel.net_timeout_s,
            retries=cfg.parallel.net_retries, log_fn=logger.log)
        store = net_lib.NetHeartbeatStore(fleet_dir, replica_id,
                                          net_client, log_fn=logger.log)
    else:
        store = HeartbeatStore(fleet_dir, process_id=replica_id,
                               log_fn=logger.log)
    # Failure-domain assignment is positional — replica i lands in cell
    # i % len(cells) — so a fleet config names its cells once and every
    # spawn (autoscaler included) is deterministically placed.
    cells = [c.strip() for c in (cfg.fleet.cell or "").split(",")
             if c.strip()] or ["default"]
    cell = cells[replica_id % len(cells)]
    phase_ref = {"phase": "warmup"}
    port_ref: dict = {}
    parsed_fault = _parse_fault(fault)

    serve_cfg = cfg.serve
    metrics = ServeMetrics()
    beats = None
    front = engine
    if parsed_fault is not None:
        front = _FaultingEngine(engine, parsed_fault[0], parsed_fault[1],
                                on_stall=lambda: beats.stall())
    batcher = MicroBatcher(
        front, buckets=serve_cfg.buckets,
        max_queue_depth=serve_cfg.max_queue_depth,
        batch_window_s=serve_cfg.batch_window_ms / 1e3,
        default_deadline_s=None if serve_cfg.deadline_ms is None
        else serve_cfg.deadline_ms / 1e3,
        metrics=metrics, logger=logger)
    beats = _BeatPublisher(store, batcher, engine,
                           cfg.fleet.heartbeat_interval_s, port_ref,
                           phase_ref, cell=cell)
    beats.start()

    response_cache = (ResponseCache(serve_cfg.cache_size)
                      if serve_cfg.cache_size > 0 else None)
    server = ThreadingHTTPServer(
        ("", serve_cfg.port),
        _make_handler(batcher, metrics, replica_id=replica_id,
                      hop="worker", logger=logger,
                      sample_rate=serve_cfg.trace_sample_rate,
                      cache=response_cache))
    port_ref["port"] = server.server_address[1]
    watcher = _SwapWatcher(fleet_dir, engine, trainer, state,
                           cfg.fleet.swap_poll_s, last_seq,
                           logger=logger, quant_ctx=quant_ctx)
    flusher = _MetricsFlusher(metrics, logger, serve_cfg.metrics_every_s,
                              alerts=alert_engine)
    accept = threading.Thread(target=server.serve_forever,
                              name="fleet-worker-accept", daemon=True)
    drained = True
    try:
        with PreemptionGuard() as guard:
            accept.start()
            watcher.start()
            flusher.start()
            phase_ref["phase"] = "serve"
            beats.beat_once()   # don't wait one interval to go routable
            print(f"[fleet] replica {replica_id} serving version "
                  f"{engine.version} on :{port_ref['port']} "
                  f"(compile_s={batcher.compile_secs})", flush=True)
            if ready_event is not None:
                ready_event.set()
            try:
                while not guard.requested and (
                        stop_event is None or not stop_event.is_set()):
                    time.sleep(0.05)
                why = (f"signal {guard.signum}" if guard.requested
                       else "stop requested")
            except KeyboardInterrupt:
                why = "keyboard interrupt"
            phase_ref["phase"] = "drain"
            beats.beat_once()
            print(f"[fleet] replica {replica_id} {why}: draining "
                  f"(deadline {serve_cfg.drain_deadline_s:.1f}s)")
            server.shutdown()
            accept.join()
            drained = batcher.drain(timeout=serve_cfg.drain_deadline_s)
    finally:
        server.server_close()
        watcher.stop()
        flusher.stop()
        beats.stop()
        if batcher._worker.is_alive():
            batcher.close()
        phase_ref["phase"] = "stopped"
        beats.beat_once()
        metrics.emit(logger, final=True)
        logger.flush()
        logger.close()
    print(f"[fleet] replica {replica_id} exiting cleanly "
          f"({'drained' if drained else 'drain deadline hit'})")
    return 0


def main_from_argv(argv) -> int:
    """``worker.py <config.json> <replica_id> [fault]`` — the spawn
    contract of the fleet controller's worker pool (a JSON config file,
    not a re-marshalled CLI, so workers can't drift from the fleet's
    flags)."""
    if len(argv) < 2:
        print("usage: python -m dml_cnn_cifar10_tpu.fleet.worker "
              "<config.json> <replica_id> [fault_kind@n]",
              file=sys.stderr)
        return 2
    from dml_cnn_cifar10_tpu.config import config_from_dict
    with open(argv[0]) as f:
        cfg = config_from_dict(json.load(f))
    fault = argv[2] if len(argv) > 2 and argv[2] else None
    return main_worker(cfg, int(argv[1]), fault=fault)


def _pin_platform() -> None:
    """Re-assert the platform the controller spawned us for. A plain
    env inheritance is not enough on hosts whose sitecustomize
    overwrites ``JAX_PLATFORMS`` at interpreter startup (the reason
    ``utils/platform.force_cpu`` exists) — so the pool passes the
    intent on a var sitecustomize doesn't touch."""
    plat = os.environ.get("DML_FLEET_WORKER_PLATFORM")
    if plat == "cpu":
        from dml_cnn_cifar10_tpu.utils.platform import force_cpu
        force_cpu()
    elif plat:
        os.environ["JAX_PLATFORMS"] = plat


if __name__ == "__main__":
    _pin_platform()
    sys.exit(main_from_argv(sys.argv[1:]))
