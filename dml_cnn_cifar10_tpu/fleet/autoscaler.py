"""Closed-loop autoscaling from the fleet's own telemetry.

The signals are the serving metrics that already exist — nothing new is
measured. Each worker appends ``serve`` window records to its own JSONL
stream (``<fleet_dir>/telemetry/replica_<id>.jsonl``) and advertises
its queue depth in every heartbeat; the autoscaler tails the streams,
aggregates one :class:`FleetSignals`, and feeds it to the pure decision
function :func:`decide`:

==================================  ===========================  ======
condition                           reading                      action
==================================  ===========================  ======
replicas below ``min_replicas``     a worker died / fleet young  up
shed fraction > ``shed_up``         admission control rejecting  up
p99 above ``serve.slo_ms``          latency objective violated   up
queue depth/replica > threshold     backpressure building        up
all quiet and above ``min``         paying for idle capacity     down
otherwise                           steady                       hold
==================================  ===========================  ======

Up-conditions are checked against ``max_replicas`` and include workers
still warming up (``starting``) so a slow spin-up is not answered with
a second, third, fourth spawn. Scale-down retires ONE replica per
decision and only when every signal is quiet — capacity exits slowly,
enters fast (the standard asymmetry: shedding user traffic costs more
than an idle worker). The controller enforces a post-action cooldown so
the loop measures the effect of one action before taking another.

``decide`` is a pure function of its inputs — the decision table above
IS the unit test (``tests/test_fleet.py``).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Sequence

#: Shed fraction above which the fleet scales up (admission control is
#: actively rejecting traffic — the loudest signal).
SHED_UP = 0.01
#: Scale-down requires p99 below this fraction of the SLO (when one is
#: configured): "comfortably inside", not "barely passing".
SLO_DOWN_FRACTION = 0.5
#: Scale-down also requires mean queue depth per replica below this.
QUIET_QUEUE_DEPTH = 1.0


@dataclasses.dataclass
class FleetSignals:
    """One aggregated reading of the fleet's load state."""

    live: int                 # replicas in the routing rotation
    starting: int             # spawned, not yet phase=serve
    mean_queue_depth: float   # per live replica, from heartbeats
    shed_fraction: float      # across replicas' last serve windows
    p99_ms: Optional[float]   # worst replica's last-window p99


@dataclasses.dataclass
class ScaleDecision:
    action: str               # "up" | "down" | "hold"
    reason: str


def decide(signals: FleetSignals, min_replicas: int, max_replicas: int,
           slo_ms: Optional[float] = None,
           scale_up_queue_depth: float = 8.0,
           alerts_active: Sequence[str] = ()) -> ScaleDecision:
    """The decision table (module docstring). Pure — no IO, no clock.

    ``alerts_active`` is the streaming alert engine's state
    (``utils/alerts.py`` rule names currently firing): a load-shaped
    alert — shed, SLO burn, or any custom rule named ``scale_up*`` —
    is one more scale-up condition, and ANY active alert vetoes
    scale-DOWN (retiring capacity during an incident is how a page
    becomes an outage). The direct signal checks stay: alerts are
    rate-limited and windowed, so they lag the raw readings by design.
    """
    total = signals.live + signals.starting
    if total < min_replicas:
        return ScaleDecision("up", "below_min")
    alert_up = [a for a in alerts_active
                if a in ("serve_shed", "fleet_shed", "serve_p99_slo")
                or a.startswith("scale_up")]
    if signals.live > 0 and total < max_replicas:
        if signals.shed_fraction > SHED_UP:
            return ScaleDecision("up", "shedding")
        if slo_ms is not None and signals.p99_ms is not None \
                and signals.p99_ms > slo_ms:
            return ScaleDecision("up", "slo_violation")
        if signals.mean_queue_depth > scale_up_queue_depth:
            return ScaleDecision("up", "queue_depth")
        if alert_up:
            return ScaleDecision("up", f"alert_{alert_up[0]}")
    if total > min_replicas and signals.starting == 0 \
            and signals.shed_fraction == 0.0 \
            and signals.mean_queue_depth < QUIET_QUEUE_DEPTH \
            and not alerts_active \
            and (slo_ms is None or signals.p99_ms is None
                 or signals.p99_ms < SLO_DOWN_FRACTION * slo_ms):
        return ScaleDecision("down", "idle")
    return ScaleDecision("hold", "steady")


def last_serve_window(jsonl_path: str,
                      tail_bytes: int = 65536) -> Optional[dict]:
    """The newest ``serve`` window record in a replica's JSONL stream
    (tail-read — these files grow for the life of the worker)."""
    try:
        size = os.path.getsize(jsonl_path)
        with open(jsonl_path, "rb") as f:
            f.seek(max(0, size - tail_bytes))
            chunk = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(chunk.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue   # the seek may have landed mid-line
        if rec.get("kind") == "serve":
            return rec
    return None


def aggregate_signals(live_views, starting: int,
                      telemetry_dir: str) -> FleetSignals:
    """Fold the live replicas' heartbeat payloads + last serve windows
    into one :class:`FleetSignals`."""
    live = list(live_views)
    depths = [v.queue_depth for v in live]
    shed = completed = 0
    p99 = None
    for v in live:
        rec = last_serve_window(os.path.join(
            telemetry_dir, f"replica_{v.replica_id}.jsonl"))
        if rec is None:
            continue
        completed += (rec.get("completed") or 0)
        shed += (rec.get("shed_queue") or 0) + (rec.get("shed_deadline")
                                                or 0)
        if rec.get("p99_ms") is not None:
            p99 = rec["p99_ms"] if p99 is None else max(p99,
                                                        rec["p99_ms"])
    total = completed + shed
    return FleetSignals(
        live=len(live), starting=int(starting),
        mean_queue_depth=(sum(depths) / len(depths)) if depths else 0.0,
        shed_fraction=(shed / total) if total else 0.0,
        p99_ms=p99)
