"""The fleet's front door: route, balance, evict, re-route.

A deliberately thin HTTP proxy (stdlib ``ThreadingHTTPServer``, same
transport reasoning as ``serve/server.py``) in front of N serve worker
replicas. The router holds NO model state — its job is membership and
placement:

- **Discovery** — workers advertise themselves by heartbeat
  (:class:`~dml_cnn_cifar10_tpu.parallel.cluster.HeartbeatStore` under
  ``<fleet_dir>``, beats carrying ``{replica_id, version, queue_depth,
  phase, port}``). Anyone who beats with ``phase == "serve"`` is in the
  rotation; the router never needs a static member list, which is what
  lets the autoscaler add workers by just spawning them.
- **Placement** — least ``queue_depth`` first (the beat payload), round
  robin among ties: cheap, heartbeat-driven load awareness without a
  second RPC. Replicas advertise a **cell** (named failure domain,
  ``--cell``); a request tagged ``X-DML-Cell`` prefers its cell's live
  replicas and fails over cross-cell when the cell has none — the
  crossing is a ``cell_route`` record and force-samples the request's
  trace so a cross-cell retry is one Perfetto flow.
- **Eviction** — a replica whose newest beat is older than
  ``replica_dead_after_s``, or that fails at the socket, leaves the
  rotation immediately (``peer_lost`` JSONL, ``reason
  replica_evicted_*``). Its in-flight requests are NOT failed back to
  the client: the proxy attempt that broke is retried on a surviving
  replica (``route_retries``), so a worker kill under load costs zero
  client errors — the tier-1 acceptance pin (``tests/test_fleet.py``).
- **Shed passthrough** — a worker 503 (its admission control) is
  returned to the client as-is, NOT retried: overload must surface as
  shed, not as the router amplifying the load 3x by re-submitting it.

The decision logic (:func:`live_views`, :func:`pick_replica`) is pure —
unit-testable without sockets or processes; the HTTP machinery is a
shell around it.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from dml_cnn_cifar10_tpu.parallel.cluster import HeartbeatStore
from dml_cnn_cifar10_tpu.utils import backoff, netfaults, reqtrace

#: Request header naming the cell a client wants served from
#: (tools/loadgen.py --target_cell sets it). Absent header = no cell
#: preference; routing is exactly the pre-cell behaviour.
CELL_HEADER = "X-DML-Cell"


@dataclasses.dataclass
class ReplicaView:
    """One replica as the router sees it, built from its latest beat."""

    replica_id: int
    port: Optional[int]
    version: Optional[str]
    queue_depth: int
    phase: str
    age_s: float
    # Median per-batch DEVICE milliseconds the worker advertises
    # (serve/metrics.py recent_device_ms): the slow-device vs
    # deep-queue disambiguator. None before the replica's first batch
    # (and on beats from workers predating the field).
    device_ms: Optional[float] = None
    # Named failure domain (--cell; beats from workers predating the
    # field land in "default", same as an unconfigured fleet).
    cell: str = "default"


def view_from_beat(beat, now: Optional[float] = None) -> ReplicaView:
    extra = beat.extra or {}
    return ReplicaView(
        replica_id=beat.process_id,
        port=extra.get("port"),
        version=extra.get("version"),
        queue_depth=int(extra.get("queue_depth") or 0),
        phase=beat.phase,
        age_s=beat.age_s(now),
        device_ms=extra.get("device_ms"),
        cell=str(extra.get("cell") or "default"))


def live_views(views: Sequence[ReplicaView], dead_after_s: float,
               exclude=()) -> List[ReplicaView]:
    """Routable replicas: beating recently, past warmup (phase
    ``serve``), with an advertised port, and not excluded (evicted /
    draining / already tried for this request)."""
    return [v for v in views
            if v.replica_id not in exclude
            and v.phase == "serve"
            and v.port
            and v.age_s <= dead_after_s]


def pick_replica(live: Sequence[ReplicaView],
                 rr: int) -> Optional[ReplicaView]:
    """Least queue depth wins; ``rr`` (the caller's monotone request
    counter) breaks ties round-robin so equally-idle replicas share
    load instead of the lowest id eating all of it."""
    if not live:
        return None
    min_depth = min(v.queue_depth for v in live)
    tied = [v for v in live if v.queue_depth == min_depth]
    return tied[rr % len(tied)]


class _RouterWindow:
    __slots__ = ("routed", "rerouted", "evictions", "shed",
                 "by_version", "t0")

    def __init__(self):
        self.routed = 0
        self.rerouted = 0
        self.evictions = 0
        self.shed = 0
        self.by_version: Dict[str, int] = {}
        self.t0 = time.perf_counter()


class RouterMetrics:
    """Routing counters, windowed + cumulative — the same dual view as
    ``serve/metrics.py``: each periodic ``fleet`` record is a true
    per-window delta (summable by the report), ``fleet_done`` is the
    run-cumulative total."""

    def __init__(self):
        self._lock = threading.Lock()
        self._win = _RouterWindow()
        self._total = _RouterWindow()

    def _bump(self, field: str, version: Optional[str] = None) -> None:
        with self._lock:
            for w in (self._win, self._total):
                setattr(w, field, getattr(w, field) + 1)
                if version is not None:
                    w.by_version[version] = \
                        w.by_version.get(version, 0) + 1

    def record_routed(self, version: Optional[str]) -> None:
        self._bump("routed", version)

    def record_rerouted(self) -> None:
        self._bump("rerouted")

    def record_eviction(self) -> None:
        self._bump("evictions")

    def record_shed(self) -> None:
        self._bump("shed")

    @property
    def total_routed(self) -> int:
        with self._lock:
            return self._total.routed

    @staticmethod
    def _snap(w: _RouterWindow, replicas: int, live: int,
              now: float) -> dict:
        return {"replicas": replicas, "live": live,
                "routed": w.routed, "rerouted": w.rerouted,
                "evictions": w.evictions, "shed": w.shed,
                "version_mix": dict(w.by_version),
                "window_s": round(now - w.t0, 3)}

    def window(self, replicas: int, live: int) -> dict:
        """Counts since the last window (the periodic fleet record)."""
        with self._lock:
            out = self._snap(self._win, replicas, live,
                             time.perf_counter())
            self._win = _RouterWindow()
        return out

    def cumulative(self, replicas: int, live: int) -> dict:
        with self._lock:
            return self._snap(self._total, replicas, live,
                              time.perf_counter())


class Router:
    """Membership + placement + the proxy loop (see module docstring)."""

    def __init__(self, fleet_dir: str, dead_after_s: float = 3.0,
                 route_retries: int = 3, route_timeout_s: float = 30.0,
                 logger=None, host: str = "127.0.0.1",
                 trace_sample_rate: float = 0.0,
                 route_backoff_s: float = 0.05):
        # process_id -1: the router reads every beat but publishes none.
        self.store = HeartbeatStore(
            fleet_dir, process_id=-1,
            log_fn=logger.log if logger is not None else None)
        self.dead_after_s = dead_after_s
        self.route_retries = max(1, int(route_retries))
        self.route_timeout_s = route_timeout_s
        # Base of the exponential between FAILED placement attempts
        # (satellite of the partition-tolerance work): a fleet-wide
        # blip must not see all retries burned in the same millisecond.
        self.route_backoff_s = max(0.0, float(route_backoff_s))
        self.logger = logger
        self.host = host
        self.trace_sample_rate = float(trace_sample_rate)
        self.metrics = RouterMetrics()
        self._lock = threading.Lock()
        self._rr = 0
        self._evicted: set = set()    # replica ids out of rotation
        self._draining: set = set()   # retiring: no NEW requests
        self._server: Optional[ThreadingHTTPServer] = None

    # -- membership -----------------------------------------------------

    def views(self, now: Optional[float] = None) -> List[ReplicaView]:
        beats = self.store.read_all()
        return [view_from_beat(b, now) for pid, b in sorted(beats.items())
                if pid >= 0]

    def live(self, extra_exclude=()) -> List[ReplicaView]:
        with self._lock:
            exclude = self._evicted | self._draining | set(extra_exclude)
        views = self.views()
        alive = live_views(views, self.dead_after_s, exclude=exclude)
        # Staleness-driven eviction: a replica that WAS routable but
        # stopped beating leaves the rotation here (socket errors evict
        # via evict() directly).
        with self._lock:
            known = {v.replica_id for v in views}
            stale = [v.replica_id for v in views
                     if v.phase == "serve"
                     and v.age_s > self.dead_after_s
                     and v.replica_id not in self._evicted]
        for rid in stale:
            self.evict(rid, "replica_evicted_stale_heartbeat")
        return [v for v in alive if v.replica_id in known]

    def evict(self, replica_id: int, reason: str) -> None:
        with self._lock:
            if replica_id in self._evicted:
                return
            self._evicted.add(replica_id)
        self.metrics.record_eviction()
        if self.logger is not None:
            self.logger.log("peer_lost", step=self.metrics.total_routed,
                            process_id=replica_id, reason=reason)
        print(f"[fleet] evicted replica {replica_id} ({reason})")

    def drain_replica(self, replica_id: int) -> None:
        """Retirement half-step: stop routing NEW requests to the
        replica while its in-flight work finishes (the worker's own
        SIGTERM drain completes it)."""
        with self._lock:
            self._draining.add(replica_id)

    def forget(self, replica_id: int) -> None:
        """Drop a retired replica's bookkeeping once its process is
        gone (so a reused id, which the pool never does, would not be
        born evicted)."""
        with self._lock:
            self._evicted.discard(replica_id)
            self._draining.discard(replica_id)

    # -- the proxy ------------------------------------------------------

    def proxy_predict(self, body: bytes,
                      trace_header: Optional[str] = None,
                      target_cell: Optional[str] = None) -> tuple:
        """Route one request; returns ``(status, payload_dict)``.

        Worker failure at the socket (refused / reset mid-read /
        timeout) evicts that replica and retries the SAME body on the
        next pick — the re-route that turns a worker kill into zero
        client errors. Consecutive failed attempts are spaced by a
        bounded exponential (``route_backoff_s`` base) so a transient
        fleet-wide blip doesn't burn the whole retry budget at once.
        Worker 4xx/5xx HTTP answers pass through (they are the worker
        speaking, not dying).

        Cells: ``target_cell`` (the ``X-DML-Cell`` header) narrows each
        pick to that cell's live replicas while any exist; when the
        cell has none the pick falls through to the whole fleet — the
        crossing logs ``cell_route`` and force-samples the trace. No
        ``target_cell`` = the pre-cell routing, record for record.

        A replica the armed network faults (``utils/netfaults.py``)
        isolate is unreachable BY DEFINITION of the partition sim —
        treated exactly like a connect error (evict + re-route) without
        burning ``route_timeout_s`` on a socket that would hang.

        Tracing: one ``rspan`` per placement ATTEMPT, buffered until
        the request resolves — a retry or a shed forces the trace, and
        the buffer means the attempts BEFORE the forcing event (the one
        that landed on the soon-dead worker) still make the stream.
        """
        ctx = reqtrace.parse(trace_header, self.trace_sample_rate)
        attempts: list = []

        def _flush_spans():
            for a in attempts:
                reqtrace.emit_span(self.logger, ctx, "router",
                                   a.pop("dur_s"), a.pop("wallclock"),
                                   **a)

        def _backoff(attempt: int) -> None:
            if self.route_backoff_s > 0 and attempt < self.route_retries:
                time.sleep(backoff.delay_s(self.route_backoff_s,
                                           self.route_backoff_s * 10,
                                           attempt + 1))

        tried: set = set()
        for attempt in range(self.route_retries + 1):
            with self._lock:
                rr = self._rr
                self._rr += 1
            candidates = self.live(extra_exclude=tried)
            pool = candidates
            if target_cell:
                in_cell = [v for v in candidates
                           if v.cell == target_cell]
                pool = in_cell or candidates
            target = pick_replica(pool, rr)
            if target is None:
                self.metrics.record_shed()
                ctx.force()
                _flush_spans()
                reqtrace.emit_span(self.logger, ctx, "router", 0.0,
                                   time.time(), attempt=attempt,
                                   shed="no_live_replicas")
                return 503, {"shed": "no_live_replicas"}
            if target_cell and target.cell != target_cell:
                # Cross-cell failover: the requested cell has no live
                # replica right now. Force-sample so the whole retry
                # chain (the in-cell attempt that died, this crossing,
                # the answer) is one trace flow.
                ctx.force()
                if self.logger is not None:
                    self.logger.log("cell_route", from_cell=target_cell,
                                    to_cell=target.cell,
                                    replica_id=target.replica_id,
                                    attempt=attempt)
            if attempt:
                self.metrics.record_rerouted()
            if netfaults.is_isolated(target.replica_id):
                # Partition sim data plane: don't dial a socket the
                # fault would hold — fail the attempt as the timeout
                # eventually would, instantly and deterministically.
                ctx.force()
                attempts.append(
                    {"dur_s": 0.0,
                     "wallclock": time.time(),
                     "attempt": attempt, "status": 0,
                     "replica_id": target.replica_id,
                     "error": "partitioned"})
                tried.add(target.replica_id)
                self.evict(target.replica_id,
                           "replica_evicted_partitioned")
                _backoff(attempt)
                continue
            req = urllib.request.Request(
                f"http://{self.host}:{target.port}/predict", data=body,
                headers={"Content-Type": "application/octet-stream",
                         reqtrace.TRACE_HEADER: ctx.header()})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(
                        req, timeout=self.route_timeout_s) as resp:
                    payload = json.loads(resp.read())
                self.metrics.record_routed(payload.get("version"))
                payload["replica_id"] = target.replica_id
                attempts.append(
                    {"dur_s": time.perf_counter() - t0,
                     "wallclock": reqtrace.wallclock_at(t0),
                     "attempt": attempt, "status": 200,
                     "replica_id": target.replica_id,
                     "version": payload.get("version")})
                _flush_spans()
                return 200, payload
            except urllib.error.HTTPError as e:
                # The worker answered: shed/size errors pass through
                # untouched (retrying a 503 would amplify overload).
                try:
                    payload = json.loads(e.read())
                except Exception:
                    payload = {"error": f"worker http {e.code}"}
                if e.code == 503:
                    self.metrics.record_shed()
                    ctx.force()
                attempts.append(
                    {"dur_s": time.perf_counter() - t0,
                     "wallclock": reqtrace.wallclock_at(t0),
                     "attempt": attempt, "status": e.code,
                     "replica_id": target.replica_id})
                _flush_spans()
                return e.code, payload
            except (urllib.error.URLError, http.client.HTTPException,
                    ConnectionError, TimeoutError, OSError):
                # The worker DIED mid-conversation (or never answered):
                # evict and re-route this same request. Force the trace
                # — a retried request is exactly what tracing is for —
                # and buffer the failed attempt's span (it shows the
                # placement on the dead worker).
                ctx.force()
                attempts.append(
                    {"dur_s": time.perf_counter() - t0,
                     "wallclock": reqtrace.wallclock_at(t0),
                     "attempt": attempt, "status": 0,
                     "replica_id": target.replica_id,
                     "error": "connect_error"})
                tried.add(target.replica_id)
                self.evict(target.replica_id,
                           "replica_evicted_connect_error")
                _backoff(attempt)
        self.metrics.record_shed()
        ctx.force()
        _flush_spans()
        reqtrace.emit_span(self.logger, ctx, "router", 0.0, time.time(),
                           shed="route_retries_exhausted")
        return 503, {"shed": "route_retries_exhausted"}

    def healthz(self) -> dict:
        views = self.views()
        live_ids = {v.replica_id for v in self.live()}
        return {
            "ok": bool(live_ids),
            "live": len(live_ids),
            "replicas": {
                str(v.replica_id): {
                    "port": v.port, "version": v.version,
                    "queue_depth": v.queue_depth, "phase": v.phase,
                    "age_s": round(v.age_s, 3),
                    "device_ms": v.device_ms,
                    "cell": v.cell,
                    "live": v.replica_id in live_ids}
                for v in views},
        }

    def emit(self, final: bool = False) -> None:
        """One ``fleet`` window record; when ``final``, the cumulative
        ``fleet_done`` follows (mirroring serve/serve_done). The window
        record carries the live replicas' advertised per-batch device
        time (``device_ms``, from their beats) so the stream answers
        slow-device-vs-deep-queue without raw beat-file spelunking —
        the telemetry_report fleet-health section renders it."""
        if self.logger is None:
            return
        views = self.views()
        live = self.live()
        device_ms = {str(v.replica_id): v.device_ms for v in live
                     if v.device_ms is not None}
        # wallclock: the clock-alignment anchor for streams with no
        # heartbeat records (tools/trace_aggregate.py falls back to it).
        self.logger.log("fleet",
                        **self.metrics.window(len(views), len(live)),
                        device_ms=device_ms, wallclock=time.time())
        if final:
            self.logger.log("fleet_done",
                            **self.metrics.cumulative(len(views),
                                                      len(live)),
                            device_ms=device_ms, wallclock=time.time())

    # -- HTTP shell -----------------------------------------------------

    def make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    from dml_cnn_cifar10_tpu.utils.metrics_registry \
                        import default_registry
                    body = default_registry().render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/healthz":
                    self._reply(200, router.healthz())
                elif self.path == "/stats":
                    # Cumulative and read-only: probing stats must not
                    # consume the periodic record's window.
                    views = router.views()
                    self._reply(200, router.metrics.cumulative(
                        replicas=len(views), live=len(router.live())))
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                code, payload = router.proxy_predict(
                    self.rfile.read(n),
                    trace_header=self.headers.get(reqtrace.TRACE_HEADER),
                    target_cell=self.headers.get(CELL_HEADER))
                self._reply(code, payload)

        return Handler

    def serve(self, port: int) -> ThreadingHTTPServer:
        """Bind + start the accept loop on a daemon thread; returns the
        server (its ``server_address[1]`` is the bound port)."""
        self._server = ThreadingHTTPServer(("", port),
                                           self.make_handler())
        threading.Thread(target=self._server.serve_forever,
                         name="fleet-router-accept", daemon=True).start()
        return self._server

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
