"""Typed configuration for the framework.

The reference keeps every hyperparameter as a module-level constant
(``cifar10cnn.py:9-27``) and exposes only cluster flags via argparse
(``cifar10cnn.py:245-273``). Here all of them are dataclass fields with the
reference values as defaults, so parity runs are the zero-config path and the
CLI can override anything.

Fidelity switches: the reference has three load-bearing quirks —
(1) ReLU applied to the logits (``cifar10cnn.py:145``),
(2) a dead LR-decay schedule (decay keyed on a never-incremented variable,
    ``cifar10cnn.py:161,216`` — effective LR is constant 0.1),
(3) eval on a single *shuffled* 128-image test batch rather than the full
    test set (``cifar10cnn.py:202,238``).
Each has a switch; ``faithful`` mode reproduces the quirk, ``fixed`` mode does
the sane thing. Defaults are faithful so parity runs match the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class DataConfig:
    """Input pipeline config. Reference: ``cifar10cnn.py:9-27,34-91``."""

    dataset: str = "cifar10"              # cifar10 | cifar100 | synthetic
    data_dir: str = "cifar10data"         # reference constant (cifar10cnn.py:26)
    image_height: int = 32                # cifar10cnn.py:15
    image_width: int = 32                 # cifar10cnn.py:16
    crop_height: int = 24                 # cifar10cnn.py:17
    crop_width: int = 24                  # cifar10cnn.py:18
    num_channels: int = 3                 # cifar10cnn.py:19
    num_classes: int = 10                 # cifar10cnn.py:20 (NUM_TARGETS)
    shuffle_buffer: int = 5000            # min_after_dequeue (cifar10cnn.py:85)
    # Reference crop is a deterministic center crop despite the "Randomly
    # Crop" comment (cifar10cnn.py:67-68). random_crop=True enables the
    # augmentation the comment intended (fixed mode).
    random_crop: bool = False
    random_flip: bool = False
    # Color jitter (the TF CIFAR-tutorial lineage the reference derives
    # from used random_brightness(63) + random_contrast(0.2, 1.8)):
    # brightness adds U[-b, b] in pixel units per image; contrast scales
    # per-channel deviation-from-mean by U[1-c, 1+c]. 0 = off.
    random_brightness: float = 0.0
    random_contrast: float = 0.0
    # Pixel normalization. The reference feeds raw 0..255 floats
    # (cifar10cnn.py:66 — cast, no scaling), which with LR 0.1 makes training
    # numerically violent; faithful default keeps that. "scale" maps to
    # [0,1]; "standardize" does per-image zero-mean/unit-var (what the TF
    # CIFAR tutorial the reference derives from actually used).
    normalize: str = "none"               # none | scale | standardize
    prefetch: int = 2                     # host->HBM prefetch depth
    seed: int = 0
    # HBM-resident path only: generate the shuffled index stream ON
    # DEVICE inside the compiled chunk (data/device_stream.py stateless
    # per-epoch pseudo-permutation keyed on the global step) — a training
    # dispatch then uploads nothing at all. The shuffle is a different
    # (equally valid) permutation than the host stream's numpy-PCG one,
    # so toggling this flag changes the data order. Default ON (round-4
    # verdict #5: throughput parity with host indices, deletes the
    # exact-resume sidecar, and ships no per-process index arrays at
    # multi-host scale); --device_index_stream=false restores the host
    # numpy-PCG stream.
    device_index_stream: bool = True
    # Use the native C++ record loader when the shared library is available;
    # falls back to the pure-NumPy path otherwise.
    use_native_loader: bool = True
    # Synthetic mode generates CIFAR-format .bin files locally (same 3073-byte
    # record layout) for air-gapped testing/benchmarking.
    synthetic_train_records: int = 2048
    synthetic_test_records: int = 512

    # Every randomized-augmentation field and its "off" value — the one
    # list ``augmented`` and ``without_augmentation`` both derive from, so
    # a new augmentation knob cannot drift between them.
    _AUG_OFF = (("random_crop", False), ("random_flip", False),
                ("random_brightness", 0.0), ("random_contrast", 0.0))

    @property
    def augmented(self) -> bool:
        """True when ANY randomized augmentation is on — the single
        source of truth for "needs a PRNG key on the device decode path"
        (ops/preprocess.py) and for the chunk builders' key threading."""
        return any(getattr(self, name) != off for name, off in self._AUG_OFF)

    def without_augmentation(self) -> "DataConfig":
        """Eval-time decode config: every randomized augmentation off."""
        return dataclasses.replace(self, **dict(self._AUG_OFF))

    @property
    def record_bytes(self) -> int:
        """1 label byte + H*W*C image bytes (cifar10cnn.py:24-25)."""
        return 1 + self.image_height * self.image_width * self.num_channels

    @property
    def input_hw(self) -> Tuple[int, int]:
        return (self.crop_height, self.crop_width)


@dataclasses.dataclass
class ModelConfig:
    """Model selection + faithful-mode switches."""

    name: str = "cnn"                     # cnn | resnet18 | resnet50 | vit_tiny
    num_classes: int = 10
    # Reference applies ReLU to the final logits (cifar10cnn.py:145). Faithful
    # mode keeps it; fixed mode emits raw logits.
    logit_relu: bool = True
    # Initializers: truncated normal sigma=0.05 (cifar10cnn.py:97-98),
    # bias constant 0.1 (cifar10cnn.py:100-101).
    init_stddev: float = 0.05
    bias_init: float = 0.1
    dtype: str = "float32"                # param dtype
    compute_dtype: str = "float32"        # activations; bfloat16 on TPU runs
    # BatchNorm knobs (ResNet configs; SURVEY §2.3 cross-replica stats).
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    # ViT-specific knobs (ignored by CNN/ResNet).
    patch_size: int = 4
    vit_dim: int = 192
    vit_depth: int = 12
    vit_heads: int = 3
    use_pallas_attention: bool = True     # Pallas flash-attention on TPU
    # "cls" = prepend a class token (standard ViT head). "mean" = no class
    # token, mean-pool the tokens — the long-context/sequence-parallel mode,
    # where the token count must divide the ``seq`` mesh axis and a lone
    # cls token would break the even sharding.
    pool: str = "cls"                     # cls | mean
    # Rematerialization: recompute each block's activations in the
    # backward pass instead of storing them (jax.checkpoint around the
    # ViT transformer block / ResNet residual block). Trades ~1 extra
    # forward of FLOPs for activation memory that stays O(1) in depth —
    # the standard long-context / deep-stack memory lever on TPU.
    remat: bool = False
    # Sequence-parallel attention strategy when the mesh's ``seq`` axis >1:
    # "ring" walks K/V shards around the ring (no head-count constraint,
    # best at very long S); "ulysses" all-to-alls seq→heads and runs one
    # dense full-sequence kernel per head slice (needs heads % seq_axis
    # == 0, best MXU utilization at moderate seq degree).
    sp_mode: str = "ring"                 # ring | ulysses
    # Sliding-window (local) attention width: None = full attention.
    # Band |row - col| < attn_window, composed with ``attn_causal`` the
    # Mistral-style local-LM mask. Applies to the ViT family's attention
    # on every path (XLA short-seq, flash kernels, ring, Ulysses); under
    # ring SP the window must not exceed the per-shard sequence length.
    attn_window: int | None = None
    # Causal (autoregressive) attention mask for the transformer blocks.
    attn_causal: bool = False
    # MLPerf-style space-to-depth stem for the ImageNet-stem ResNets:
    # [B,224,224,3] re-laid-out to [B,112,112,12] and the 7x7/2 stem conv
    # replaced by the equivalent 4x4/1 conv on the re-laid tensor (the
    # 7x7 kernel embeds in the 4x4x12 class, zero-padded to 8x8). C=3
    # tiles the MXU contraction at ~2% occupancy; 12 channels x 16 taps
    # quadruple it. Changes the stem param shape (checkpoints don't
    # interchange across this flag).
    resnet_s2d: bool = False
    # ResNet normalization: "bn" (reference semantics — cross-replica
    # BatchNorm) or "nf" (normalizer-free: scaled weight standardization
    # + SkipInit residual scalars, models/resnet.py). The round-4
    # roofline showed 76.5% of ResNet-50 step time bandwidth-bound with
    # BN's stats reductions + normalize store/re-read a big share of the
    # bytes; "nf" removes those passes entirely — the byte-reduction
    # rung. Different semantics than the BN ladder rows (no running
    # stats; checkpoints don't interchange across this flag).
    resnet_norm: str = "bn"
    # GPipe microbatches per step under pipeline parallelism (0 = one per
    # stage). The bubble fraction is (M+P-1)/M: at the M=P default every
    # stage idles ~half the ticks; M = 4P costs 1/4 the bubble in
    # exchange for microbatches 1/4 the size. The global batch must be
    # divisible by data_axis * M.
    pipe_microbatches: int = 0
    # Pipeline schedule: "1f1b" (default — bubbles skipped, recompute
    # backward: 3F+1B, minimal O(P·microbatch) memory; measured faster
    # than the ring at every benched geometry), "1f1b_ring" (2F+1B
    # residual-ring backward — opt-in; see parallel/pipeline.py's
    # measured verdict), or "gpipe" (the round-2 baseline: always-on
    # stage compute, autodiff through the scan; kept for comparison
    # benches).
    pipe_schedule: str = "1f1b"
    # Mixture-of-Experts (model name "vit_moe"): every block's MLP becomes
    # a routed expert bank (ops/moe.py) — moe_top_k=1 Switch routing,
    # 2 GShard — with experts sharded over the ``model`` mesh axis
    # (expert parallelism).
    moe_experts: int = 0                  # 0 = dense MLP
    # MoE dispatch/combine formulation (ops/moe.py): "einsum" ([T,E,C]
    # one-hot contractions — the all-MXU, ep-proven path whose dispatch
    # GSPMD compiles into the expert all-to-all) or "scatter"
    # ((expert, slot)-indexed scatter/gather — O(T·D) instead of the
    # einsum pair's O(T²·f·D); measured 2.28x vit_moe step throughput
    # at 16k tokens on one chip, BASELINE.md round 5). Identical
    # semantics, numerically equivalent (pinned to ~1e-5 by
    # test_scatter_dispatch_matches_einsum — reduction orders differ,
    # so outputs are close, not bit-identical).
    moe_dispatch: str = "einsum"
    moe_top_k: int = 1                    # 1 = Switch, 2 = GShard routing
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01            # load-balance loss weight


@dataclasses.dataclass
class OptimConfig:
    """Optimizer/schedule. Reference: ``cifar10cnn.py:21-23,159-164``."""

    learning_rate: float = 0.1            # cifar10cnn.py:21
    lr_decay: float = 0.9                 # cifar10cnn.py:22
    decay_every: int = 250                # NUM_GENS_TO_WAIT (cifar10cnn.py:23)
    staircase: bool = True                # cifar10cnn.py:161
    # Faithful mode: the reference's decay is keyed on a variable that is
    # never incremented (cifar10cnn.py:216), so the effective LR is a
    # constant 0.1. dead_lr_decay=True reproduces that; False applies the
    # schedule the code *meant* (keyed on the global step).
    dead_lr_decay: bool = True
    momentum: float = 0.0                 # reference uses plain SGD
    weight_decay: float = 0.0
    # Schedule family: "exponential" is the reference's (with the
    # dead_lr_decay fidelity switch above); "cosine" (half-cosine to 0
    # over cosine_decay_steps) is the ViT/ResNet ladder standard;
    # "constant" is flat. warmup_steps prepends a linear ramp to any of
    # them.
    schedule: str = "exponential"         # exponential | cosine | constant
    warmup_steps: int = 0
    cosine_decay_steps: int = 0
    # Optimizer family. "sgd" (+ optional momentum) is the reference's;
    # "adamw" (decoupled weight decay, bias-corrected moments) is the
    # transformer-ladder standard; "lars"/"lamb" add the per-layer trust
    # ratio that makes LARGE global batches trainable — the natural
    # companion of wide ``data``-axis scaling (You et al. 2017/2019);
    # "adafactor" (Shazeer & Stern 2018) factors the second moment into
    # row/col statistics — O(n+m) optimizer state per matrix instead of
    # Adam's O(n*m), the TPU-era memory choice for large models.
    optimizer: str = "sgd"        # sgd | adamw | lars | lamb | adafactor
    # LARS trust coefficient (eta in the paper) and norm-guard epsilon.
    lars_trust_coef: float = 0.001
    lars_eps: float = 1e-9
    # Label smoothing ε for the CE loss (0 = reference parity).
    label_smoothing: float = 0.0
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    adam_eps: float = 1e-8
    grad_clip_norm: Optional[float] = None
    # Async-PS staleness emulation (SURVEY §2.3's one semantic delta:
    # the reference's workers compute gradients on parameters that are
    # up to W-1 updates old, W = worker count — cifar10cnn.py:162,
    # no SyncReplicasOptimizer). S >= 2 reproduces that staleness
    # DETERMINISTICALLY: gradients are taken at a round-robin snapshot
    # S-1 updates old and applied to the live params, so async-vs-sync
    # convergence can be compared exactly. 0/1 = synchronous (default).
    # Costs S extra param copies in the optimizer state.
    async_staleness: int = 0
    # Exponential moving average of the params, updated every step and
    # used for EVAL only (the train step keeps optimizing the raw
    # params). 0 disables. The standard ViT/ResNet recipe stabilizer; no
    # reference counterpart.
    ema_decay: float = 0.0
    # Gradient accumulation: split each global batch into this many
    # microbatches inside the compiled step (lax.scan), average the grads,
    # apply ONE optimizer update. Trains large effective batches in bounded
    # activation memory (no reference counterpart — the reference's batch
    # always fits; this is a scale capability).
    grad_accum: int = 1
    # Cross-replica sharding of the WEIGHT UPDATE (arxiv 2004.13336;
    # docs/SHARDING.md). "none" = fully replicated params + optimizer
    # state (the historical layout). "zero1" = optimizer moments (+ EMA)
    # allocated sharded 1/|data| from init on; the step reduce-scatters
    # grads over ``data``, each replica updates its shard, and the new
    # params all-gather for the next forward — same math as replicated
    # to reduction-reorder tolerance (≤1e-6, pinned), checkpoints
    # interchange across modes. Needs the GSPMD (default) step; does
    # not compose with --fsdp (which already shards the update state)
    # or --async_staleness.
    optimizer_sharding: str = "none"     # none | zero1
    # Fused single-pass SGD update (ops/optimizer.py): momentum + weight
    # decay + LR applied in ONE pass over the param bytes — a Pallas TPU
    # kernel with an identical-math XLA fallback selected by platform
    # (bit-equal to the tree_map chain; PARITY.md). False restores the
    # historical per-transform tree_map chain.
    fused_optimizer: bool = True


@dataclasses.dataclass
class ParallelConfig:
    """Mesh / distribution. Replaces the PS cluster (``cifar10cnn.py:184-196``).

    The reference's asynchronous parameter-server data parallelism becomes
    synchronous SPMD data parallelism: batch sharded over the ``data`` mesh
    axis, gradient all-reduce compiled into the step (psum over ICI). The
    ``model`` axis enables tensor parallelism for the larger configs.
    """

    data_axis: int = -1                   # -1 => all remaining devices
    model_axis: int = 1                   # tensor-parallel degree
    seq_axis: int = 1                     # sequence/context-parallel degree
    pipe_axis: int = 1                    # pipeline-parallel degree (stages)
    # Multi-host bootstrap (replaces ClusterSpec/Server, cifar10cnn.py:188-189)
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    # Coordinator bootstrap hardening (parallel/multihost.py): how long
    # one jax.distributed.initialize attempt may wait for the
    # coordinator, and how many attempts (with the shared bounded
    # exponential backoff, utils/backoff.py) before a slow-to-start
    # coordinator becomes a real failure.
    coordinator_timeout_s: float = 60.0
    coordinator_retries: int = 3
    # Cluster-resilience layer (parallel/cluster.py; docs/RESILIENCE.md
    # multi-host section). cluster_dir enables it: a shared directory
    # (NFS/GCS-fuse in production, a tmpdir in the CPU simulation)
    # holding per-process heartbeat beats and the chief's restart
    # decisions. None = layer off (the default; single-process runs
    # don't need it).
    cluster_dir: Optional[str] = None
    # Background beat cadence. Beats publish from a daemon thread so a
    # host that is merely compiling/blocked still looks ALIVE.
    heartbeat_interval_s: float = 0.5
    # Dispatch-seam overrun after which the watchdog starts classifying
    # peers (straggler telemetry for peers beating-but-behind).
    straggler_after_s: float = 2.0
    # A peer whose newest beat is older than this is declared lost —
    # the run aborts deterministically (PeerLostError) instead of
    # blocking in an XLA collective forever.
    peer_dead_after_s: float = 10.0
    # Armed-seam duration after which the watchdog presumes the main
    # thread is wedged inside a collective and aborts THIS process
    # (os._exit) after logging — a loud corpse beats a silent hang.
    collective_timeout_s: float = 120.0
    # Coordinated elastic restart shrinks the world by the lost hosts;
    # below this floor the chief halts instead of continuing degraded.
    min_hosts: int = 1
    # Elastic scale-UP: let returning (or brand-new) hosts back in. A
    # host a restart decision excluded announces itself with a
    # `rejoin`-phase heartbeat instead of fencing; the chief records a
    # monotone-epoch EXPAND decision growing the world to the live
    # hosts, and everyone re-enters restore at the larger size (the
    # device index stream reshards deterministically — no per-host
    # sidecar state to migrate). Off = the PR-4 shrink-only contract:
    # once evicted, fenced forever.
    elastic_expand: bool = False
    # Peer-redundant in-memory shards (ckpt/peerstore.py;
    # docs/RESILIENCE.md diskless-recovery section). At every checkpoint
    # boundary each host pushes its local shard payload to its
    # ring-successor's replica inbox under <cluster_dir>/replicas, so an
    # elastic restart can reconstruct the lost host's state from a
    # surviving peer instead of walking disk checkpoints. Requires
    # cluster_dir; a 1-process world degrades to a no-op (the flag stays
    # legal). Off = every restore reads disk, exactly as before.
    peer_redundancy: bool = False
    # Replica retention: committed replica step-dirs kept per owner
    # before the push thread prunes the oldest.
    replica_keep: int = 2
    # Coordination transport (parallel/net.py; docs/RESILIENCE.md
    # transport-selection section). "file": the shared-directory store
    # above — the n=1/test fallback and the shared-filesystem default.
    # "net": the same HeartbeatStore/RestartCoordinator contracts over
    # a stdlib-HTTP coordination service hosted by process 0 over
    # cluster_dir; every operation gets a bounded timeout, bounded
    # retries, and classified errors, so a dead/partitioned
    # coordinator degrades into the ordinary peer_lost/eviction paths
    # instead of a hang.
    cluster_transport: str = "file"
    # Per-request socket timeout of the net transport. The lockstep
    # sims run 0.5s; production WANs want the default.
    net_timeout_s: float = 5.0
    # Extra attempts per operation (bounded backoff between attempts)
    # before a transport failure is surfaced.
    net_retries: int = 2
    # Simulation only: make the dispatch seam a software barrier over
    # the heartbeat store (wait for every live peer to reach the local
    # step) so multi-process CPU runs without real collectives still
    # exercise straggler/hang/host-loss classification in lockstep.
    # Real multi-host runs leave this off — XLA already enforces it.
    cluster_lockstep: bool = False
    # Explicit shard_map + lax.psum step instead of jit auto-partitioning.
    explicit_collectives: bool = False
    # ZeRO/FSDP: shard params + optimizer moments over the ``data`` axis
    # (parallel/shardings.py:_add_fsdp). State memory scales 1/|data|;
    # GSPMD all-gathers weights before compute and reduce-scatters grads.
    # Composes with the model/seq/pipe axes. No reference counterpart —
    # the PS already "sharded" state round-robin over PS tasks
    # (cifar10cnn.py:195-196); this is the SPMD-native form of that idea.
    fsdp: bool = False
    # Partition-rule override (parallel/shardings.py engine;
    # docs/SHARDING.md grammar): ordered ";"-separated "regex=spec"
    # rules replacing the model's default table — specs are
    # comma-separated per-dim axis names, right-aligned ("-"/"*"/empty =
    # unsharded dim, "^" prefix = left-aligned, empty spec =
    # replicated). None keeps the model's built-in rules.
    partition_rules: Optional[str] = None
    # Strict rule matching: a leaf no rule covers is a build-time error
    # instead of silently replicating (applies to the override above
    # AND the built-in tables, which all end in a catch-all).
    partition_rules_strict: bool = False
    # Print the which-rule-matched-which-param report (path, shape,
    # matching rule, resulting spec) at Trainer build.
    partition_report: bool = False


@dataclasses.dataclass
class ServeConfig:
    """Serving runtime (``--mode serve``, ``serve/`` package).

    No reference counterpart at all — the reference's only output is a
    checkpoint directory (``cifar10cnn.py:222``). These knobs shape the
    dynamic micro-batcher documented in ``docs/SERVING.md``.
    """

    # Pre-compiled batch sizes. Each bucket jit-compiles once at warmup;
    # a request batch pads up to the smallest bucket that fits. More
    # buckets = tighter padding waste, more compiles and executable
    # cache; powers-of-~4 cover the range well.
    buckets: Tuple[int, ...] = (1, 8, 32, 128)
    # Admission control: submits beyond this queue depth are rejected
    # immediately (ShedError) instead of growing an unbounded backlog —
    # bounded worst-case queue wait, shed load instead of collapsing.
    max_queue_depth: int = 256
    # Max extra latency the batcher may add waiting to fill a batch:
    # the head request of a batch waits at most this long before
    # dispatch. Under saturation batches fill instantly and the window
    # never engages.
    batch_window_ms: float = 2.0
    # Per-request deadline: requests still queued past it are shed at
    # dispatch time (the client already gave up — don't spend device
    # lanes on them). None = no deadline.
    deadline_ms: Optional[float] = None
    # HTTP port for --mode serve (0 = ephemeral, the chosen port is
    # printed at startup).
    port: int = 8000
    # Explicit artifact to serve. None = <log_dir>/model.jaxexport when
    # present, else restore the latest checkpoint and serve live params.
    artifact_path: Optional[str] = None
    # Cadence of `serve` JSONL window records while the server runs.
    metrics_every_s: float = 5.0
    # Graceful-shutdown budget: on SIGTERM/SIGINT the server stops
    # accepting, lets already-queued batches finish for at most this
    # long, sheds the remainder, flushes metrics, and exits 0
    # (serve/server.py; reuses PreemptionGuard).
    drain_deadline_s: float = 5.0
    # Latency objective for the serving path (milliseconds at p99).
    # Purely declarative for a single server; under --mode fleet the
    # autoscaler treats a p99 above it as a scale-up signal
    # (fleet/autoscaler.py). None = no objective.
    slo_ms: Optional[float] = None
    # Head-sampling rate for distributed request tracing
    # (utils/reqtrace.py; docs/OBSERVABILITY.md request-tracing
    # section): this fraction of requests emit one `rspan` JSONL
    # record per hop (client, router attempt, worker, batcher queue,
    # engine dispatch, batch). Shed or retried requests are
    # force-sampled regardless. 0 = off.
    trace_sample_rate: float = 0.0
    # Quantized serving path (quant/ package, docs/QUANT.md): "int8"
    # serves the post-training-quantized forward (per-channel weight
    # scales + calibrated activation scales, XLA-native int8 compute);
    # versions carry a "+int8" suffix. None = float serving.
    quantize: Optional[str] = None
    # Eval-stream batches (of 64) the activation calibration observes.
    # More batches = tighter amax estimates; the holdout the publish
    # gate scores on is drawn disjointly after them.
    quant_calib_batches: int = 4
    # The pinned accuracy contract: an int8 candidate whose holdout
    # top-1 trails float top-1 by more than this FRACTION (0.005 =
    # 0.5%) is rejected at publish time (`quant_rejected` JSONL) and
    # the previous version keeps serving.
    quant_max_delta: float = 0.005
    # Exact-match response cache: LRU over (input digest, serving
    # version) entries; hits bypass the batcher entirely and count as
    # `cache_hit` in serve windows. Flushed whenever the serving
    # version changes, so a stale version can never answer. 0 = off.
    cache_size: int = 0


@dataclasses.dataclass
class FleetConfig:
    """Serving fleet (``--mode fleet``, ``fleet/`` package).

    One router/load-balancer process fronting N serve worker replicas
    (each a :class:`~serve.engine.ServingEngine` subprocess), with
    heartbeat liveness, zero-downtime checkpoint hot-swap, and a
    closed-loop autoscaler — docs/SERVING.md fleet section.
    """

    # Replica count bounds the autoscaler operates within. The pool
    # starts min_replicas workers; a fleet below min is always scaled
    # back up (the self-healing path after a worker death).
    min_replicas: int = 2
    max_replicas: int = 4
    # Router HTTP port (0 = ephemeral, printed at startup). Workers
    # always bind ephemeral ports and advertise them via heartbeats.
    port: int = 8100
    # Fleet coordination directory (heartbeats, the published-version
    # file, per-replica telemetry). None = <log_dir>/fleet. Shared
    # filesystem in production, a tmpdir in tests — same contract as
    # --cluster_dir.
    dir: Optional[str] = None
    # Worker beat cadence and the staleness threshold past which the
    # router evicts a replica and re-routes its traffic. Beats carry
    # {replica_id, version, queue_depth, phase, port}.
    heartbeat_interval_s: float = 0.25
    replica_dead_after_s: float = 3.0
    # Worker-side poll cadence on the published-version file, and
    # publisher-side watch cadence on the checkpoint dir.
    swap_poll_s: float = 0.25
    publish_poll_s: float = 0.5
    # Trainer-side publish hook: when true, every committed checkpoint
    # (integrity sidecar included) is published to <fleet dir> for the
    # online train-and-serve scenario (train/loop.py). The fleet's own
    # directory publisher watches the checkpoint dir regardless.
    publish: bool = False
    # Closed-loop autoscaler (fleet/autoscaler.py): decision cadence,
    # post-decision cooldown, and the queue-depth-per-replica level
    # treated as a scale-up signal. Decisions additionally key on shed
    # fraction and p99 vs serve.slo_ms from the replicas' serve JSONL
    # windows. autoscale=False pins the fleet at min_replicas (deaths
    # are still replaced — below-min always scales up).
    autoscale: bool = True
    autoscale_every_s: float = 2.0
    scale_cooldown_s: float = 10.0
    scale_up_queue_depth: float = 8.0
    # Max re-route attempts for one client request before the router
    # sheds it (each failed attempt also evicts the failing replica).
    route_retries: int = 3
    # Base inter-attempt delay of the router's bounded retry backoff
    # (utils/backoff.py, capped at 10x): a flapping replica must not
    # ping-pong a request across survivors at CPU speed.
    route_backoff_s: float = 0.05
    # Per-attempt router->worker proxy timeout.
    route_timeout_s: float = 30.0
    # Cadence of `fleet` JSONL window records from the router.
    metrics_every_s: float = 2.0
    # Test/drill hook: "<replica_id>:<kind>@<n>" arms utils/faults.py
    # kind (host_lost | heartbeat_stall) on that replica after n batch
    # dispatches — the fleet analogue of --fault_spec. None disables.
    worker_fault: Optional[str] = None
    # Named cells (comma-separated, e.g. "us-east,us-west"): replica i
    # belongs to cell i % len(cells), advertises it in its heartbeat,
    # and the router prefers a request's target cell (X-DML-Cell
    # header / loadgen --target_cell), failing over cross-cell — with
    # a `cell_route` record and a force-sampled trace — only when the
    # target cell has no live replica. One cell = the old behavior.
    cell: str = "default"


@dataclasses.dataclass
class RuntimeConfig:
    """Unified multi-job runtime (``--mode run``, ``runtime/`` package).

    One :class:`~runtime.core.Runtime` per process owns the mesh, the
    telemetry stream/registry, the alert engine, the stats server, and
    the serving compile cache exactly once; a job scheduler runs typed
    jobs (train / eval / serve / finetune) concurrently on that shared
    substrate — docs/RUNTIME.md.
    """

    # Comma-separated job spec: which jobs the runtime starts. "train"
    # and any triggered "finetune" are task jobs (the runtime exits when
    # they drain); "serve" and "eval" are service jobs (they run until
    # the task jobs finish, then stop). FineTuneJobs are never listed —
    # they are born from alert triggers (see finetune_steps).
    jobs: str = "train,serve"
    # EvalJob cadence: re-evaluate the latest published weights every
    # this many seconds (service job; needs "eval" in jobs).
    eval_every_s: float = 2.0
    # Test batches per EvalJob tick (each is one serving forward).
    eval_batches: int = 1
    # Pre-compile the serving engine's bucket programs at first publish.
    # Off by default: warmup fetches results (jax.device_get) and the
    # runtime's train path must keep the fetch-parity invariant — the
    # request path compiles lazily instead.
    serve_warmup: bool = False
    # Alert→job control loop: an EMITTED alert firing enqueues a
    # FineTuneJob continuing training for this many extra steps from the
    # last in-process train state (zero checkpoint reads when the
    # TrainJob ran in this process). 0 disables triggering.
    finetune_steps: int = 0
    # Comma-separated alert rule names that may trigger a FineTuneJob.
    # None = any emitted firing triggers (budget permitting).
    finetune_rules: Optional[str] = None
    # Lifetime budget of triggered FineTuneJobs per runtime.
    max_finetunes: int = 1
    # Where the runtime advertises its live state (bound serve port,
    # last published version) for tools/loadgen.py --runtime discovery.
    # None = <log_dir>/runtime.json.
    state_path: Optional[str] = None


@dataclasses.dataclass
class AutopilotConfig:
    """Alert-driven remediation (``--autopilot``, ``autopilot/``
    package; docs/AUTOPILOT.md).

    When enabled, an :class:`~autopilot.engine.AutopilotEngine`
    attaches to the alert engine's trigger seam and answers every
    emitted alert firing that matches a policy with a remediation
    action — rollback with LR scaling, memory shrink + recompile
    through the compile cache, fleet scale-up + tier shed, raising
    replica_keep — each gated by a per-policy cooldown and one global
    budget, and each recorded as a ``remediation`` JSONL record linked
    to the firing alert's id and its postmortem bundle.
    """

    enabled: bool = False
    # Policy table override (autopilot/engine.py grammar):
    # ";"-separated "name=pattern[|pattern...]->action[:k=v,...]
    # [@cooldown[s]]" where pattern fnmatches alert rule names,
    # action is one of rollback | shrink_memory | scale_up_shed |
    # raise_replica_keep, and @N is a step cooldown (@Ns = seconds).
    # None/empty = the built-in default table.
    policies: Optional[str] = None
    # Global remediation budget shared by all policies (the
    # --max_finetunes counter pattern generalized): once spent, every
    # further qualifying firing is answered by an explicit
    # suppressed_budget record and the plain alert stands.
    budget: int = 8


@dataclasses.dataclass
class TrainConfig:
    """Training driver. Reference: ``cifar10cnn.py:11-14,219-242``."""

    batch_size: int = 128                 # per-step GLOBAL batch (cifar10cnn.py:13)
    total_steps: int = 20000              # GENERATIONS (cifar10cnn.py:14)
    output_every: int = 200               # OUTPUT_EVERY (cifar10cnn.py:11)
    eval_every: int = 500                 # EVAL_EVERY (cifar10cnn.py:12)
    # Faithful mode evaluates one shuffled test batch (cifar10cnn.py:202,238);
    # fixed mode sweeps the full test set.
    eval_full_test_set: bool = False
    log_dir: str = "/tmp/train_logs"      # checkpoint dir (cifar10cnn.py:269-272)
    checkpoint_every: int = 1000          # steps; MTS default was 600s wall-clock
    # Wall-clock checkpoint cadence IN ADDITION to the step cadence — the
    # faithful MTS behavior (save_checkpoint_secs=600 default at
    # cifar10cnn.py:222). None disables the clock trigger. Multi-host runs
    # agree on it at the preemption-sync boundary (train/loop.py).
    checkpoint_every_secs: Optional[float] = None
    keep_checkpoints: int = 3
    # Checkpoint codec: "msgpack" (single flax file), "orbax" (the
    # JAX-ecosystem standard directory format — interoperable with
    # external orbax tooling), or "sharded" (per-process shard files,
    # the pod-scale path: no full-state gather, each process writes
    # O(state/N) bytes — ckpt/sharded.py). Restore auto-detects per
    # checkpoint. orbax is single-process only: its save is itself a
    # collective, which the chief-only writer would deadlock
    # (ckpt/checkpoint.py).
    ckpt_format: str = "msgpack"
    # Bounded thread-pool size for the sharded codec's concurrent
    # per-shard file IO (ckpt/sharded.py): saves split the local
    # payload across up to this many part files written in parallel,
    # restores read+verify+unpack shard files in parallel — elastic
    # transitions at large world sizes become network-bound, not
    # serialization-bound. 1 = fully serial (bit-identical results
    # either way; per-shard sha256 sidecars verify each file before
    # assembly).
    shard_io_threads: int = 4
    # Wall-clock budget for restore_checkpoint's newest→oldest fallback
    # walk (ckpt/checkpoint.py): a walk that exceeds it raises a
    # classified ckpt_restore error instead of silently scanning a huge
    # retention dir forever. 0 = no deadline.
    restore_deadline_s: float = 0.0
    # Overlap checkpoint serialize+write with training on a background
    # writer thread (the device->host fetch stays synchronous — donated
    # step buffers would otherwise race the reader).
    async_checkpoint: bool = False
    # Steps per device dispatch. >1 switches the Trainer to the chunked
    # path (parallel/step.py:make_train_chunk): lax.scan over K stacked
    # batches per dispatch, host ships raw uint8, decode/augment fused on
    # device — the dispatch-bound small-model regime needs this to keep
    # the MXU fed. output/eval/checkpoint cadences and total_steps must be
    # multiples of K so every observable boundary falls on a dispatch edge.
    steps_per_dispatch: int = 1
    # With steps_per_dispatch > 1, keep the whole uint8 dataset resident
    # in HBM and ship only shuffled index arrays (~10 KB/chunk) — the
    # device does the gather+decode (measured ~16x over the host-fed
    # chunk path on the reference CNN). Multi-host runs replicate the
    # FULL split into every process's HBM and each process contributes
    # its slice of the global index array (local shard rows translate to
    # full-split rows; bit-identical to the host-fed path by test).
    # Falls back to host-fed raw chunks when the full split exceeds
    # resident_data_max_bytes, or under the native loader (its
    # bounded-shuffle stream has no index view).
    resident_data: bool = True
    resident_data_max_bytes: int = 2_000_000_000
    # Multi-host runs agree on the preemption flag every this many steps
    # (a host-level allgather over DCN): under synchronous SPMD no process
    # may leave the step loop alone or the peers hang in the next
    # collective. Single-process runs react to the signal immediately.
    preempt_sync_every: int = 10
    # Failure detection: halt at the next metrics boundary when the train
    # loss goes non-finite, WITHOUT checkpointing the poisoned state (the
    # last good checkpoint stays the resume point). Off by default —
    # faithful-mode parity runs NaN by reference hyperparameter design
    # (LR 0.1 on raw 0-255 pixels) and must keep running like the
    # reference does.
    check_numerics: bool = False
    # What a check_numerics detection DOES (docs/RESILIENCE.md):
    # "halt" raises without checkpointing the poisoned state (the
    # original behavior); "skip" discards every update since the last
    # finite metrics boundary (a device-side snapshot kept at each
    # finite boundary) and keeps training forward; "rollback" raises a
    # classified failure the run supervisor (train/supervisor.py)
    # answers by restoring the last good checkpoint, rewinding the
    # exact-resume data state, and retrying with backoff. skip and
    # rollback share the recovery_retries budget and degrade to halt
    # when it is exhausted.
    on_nonfinite: str = "halt"            # halt | skip | rollback
    # Shared recovery budget: max skip events inside one fit() AND max
    # supervisor restart attempts across a run. Exhausted => halt.
    recovery_retries: int = 3
    # Supervisor restart backoff: base * 2^(attempt-1), capped.
    recovery_backoff_s: float = 0.5
    recovery_backoff_max_s: float = 30.0
    # Progress-based retry-budget reset: when > 0 and the newest
    # checkpoint has advanced by at least this many steps since the
    # budget was last charged, the supervisor's attempt counter resets
    # to 0 before the next failure is judged — long runs absorbing many
    # WELL-SPACED faults keep recovering, while a fault burst still
    # exhausts the budget and degrades to halt. 0 (default) keeps the
    # historical lifetime budget.
    retry_budget_window: int = 0
    # LR multiplier applied at each supervisor rollback of a non-finite
    # failure (1.0 = keep the configured LR). A deterministically
    # diverging run needs the step size reduced, not just replayed.
    rollback_lr_scale: float = 1.0
    # Deterministic fault injection (utils/faults.py):
    # "kind@step,..." with kinds nan | ckpt_corrupt | sigterm |
    # data_stall — each fires once at the first dispatch seam at/after
    # its step. Test/drill tooling; None disables.
    fault_spec: Optional[str] = None
    # Wrap fit() in the run supervisor (train/supervisor.py): classified
    # recoverable failures restore the last verified checkpoint and
    # resume instead of killing the run. Per-process scope — multi-host
    # whole-job restarts stay the scheduler's job.
    supervise: bool = False
    # Persistent compilation cache + AOT warm-start (compilecache/;
    # docs/COMPILECACHE.md). A directory holding cached programs keyed
    # by (StableHLO hash, mesh, shardings, donation, compute dtype,
    # jax/backend version): supervisor restarts, elastic re-entries,
    # and bench/serve warmups warm-start instead of recompiling —
    # time-to-first-step after a fault drops from the compile cost to a
    # disk load (jax's native persistent cache under <dir>/xla carries
    # the warm start; raw executable deserialization is opt-in per
    # backend). Fail-open: a corrupt/unwritable cache degrades to plain
    # recompiles, never to a failed run. None = off (every seam
    # compiles exactly as before).
    compile_cache_dir: Optional[str] = None
    # LRU size bound for the cache directory, applied after each store.
    compile_cache_max_bytes: int = 2_000_000_000
    metrics_jsonl: Optional[str] = None   # structured metrics sink
    # Alert-triggered flight recorder (utils/flightrec.py;
    # docs/OBSERVABILITY.md flight-recorder section). postmortem_dir
    # arms it: a bounded in-memory ring of the last flightrec_size
    # records (fed from the logger's observer hook — zero new
    # instrumentation) is snapshotted into an atomic post-mortem
    # bundle directory whenever a streaming alert FIRES, one bundle
    # per firing (suppressed re-fires capture nothing). Training
    # captures also arm a one-shot devprof window. None = off.
    postmortem_dir: Optional[str] = None
    flightrec_size: int = 256
    # Live metrics export (utils/metrics_registry.py;
    # docs/OBSERVABILITY.md "Live metrics"): serve `GET /metrics`
    # (Prometheus text exposition of the process-local registry) from a
    # lightweight stats-HTTP thread — the trainer's only HTTP surface.
    # 0 = off (default). `--mode serve` and the fleet router expose
    # /metrics on their existing HTTP servers instead.
    stats_port: int = 0
    # Custom streaming alert rules (utils/alerts.py grammar) layered
    # over the built-in defaults: ";"-separated
    # "name=expr[@window][!severity]" where expr is
    # "kind.field OP value" (threshold over consecutive records),
    # "rate(kind[.field=value]) >= N" (trailing step/second window),
    # or "absent(kind)" (no record for @Ns). Firing emits rate-limited
    # `alert` / `alert_resolved` JSONL records. None = built-ins only.
    alert_rules: Optional[str] = None
    # Run-health telemetry (utils/telemetry.py): host-loop span tracing
    # (compile, data wait, dispatch, drain, eval, checkpoint, preemption
    # sync), cumulative goodput fractions, and HBM snapshots — all riding
    # the JSONL stream at the existing metrics boundaries, zero extra
    # device fetches. Off by default: the span context managers then
    # reduce to a shared no-op.
    telemetry: bool = False
    # Chrome trace-event file of the host-loop spans (Perfetto-loadable
    # next to the XLA trace from profile_dir). Needs telemetry=True;
    # non-chief processes write <path>.task<N>.
    trace_events_path: Optional[str] = None
    # Training-health scalars compiled INTO the step (parallel/step.py):
    # global grad norm, param norm, update ratio — they ride the fused
    # boundary fetch (no extra round trips) into the train JSONL records.
    health_metrics: bool = False
    # Per-chip peak TFLOP/s for the MFU metric (e.g. ~49 fp32 / 197 bf16
    # on v5e). None logs achieved TFLOP/s only.
    peak_tflops: Optional[float] = None
    # TensorBoard event-file dir (chief only) — the MTS wrote summaries to
    # --log_dir by default (cifar10cnn.py:222); opt-in here.
    tensorboard_dir: Optional[str] = None
    seed: int = 0
    profile_dir: Optional[str] = None     # jax.profiler trace output
    # Device-time attribution window (utils/devprof.py): "N:K" captures
    # a programmatic jax.profiler trace from global step N for K steps
    # (stopping at the next DRAINED metrics boundary so the window
    # closes on quiesced devices), parses it host-side, and emits
    # per-op/per-lane `devtime` JSONL records (top-k ops, compute vs
    # collective vs infeed buckets). Writes under --profile_dir when
    # set, else <log_dir>/devprof. None = off. Unlike --profile_dir
    # alone (whole-run capture, UI analysis), this is a bounded window
    # with the analysis built in.
    profile_at_steps: Optional[str] = None

    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    fleet: FleetConfig = dataclasses.field(default_factory=FleetConfig)
    runtime: RuntimeConfig = dataclasses.field(default_factory=RuntimeConfig)
    autopilot: AutopilotConfig = dataclasses.field(
        default_factory=AutopilotConfig)


#: TrainConfig's nested dataclass fields, the single list the JSON
#: round-trip below and any future config tooling derive from.
_SUBCONFIGS = {"data": DataConfig, "model": ModelConfig,
               "optim": OptimConfig, "parallel": ParallelConfig,
               "serve": ServeConfig, "fleet": FleetConfig,
               "runtime": RuntimeConfig, "autopilot": AutopilotConfig}


def config_to_dict(cfg: TrainConfig) -> dict:
    """Plain-JSON-serializable dict of the full config tree. The fleet
    controller ships worker configs through this (one file, no CLI
    re-marshalling); ``config_from_dict`` inverts it."""
    return dataclasses.asdict(cfg)


def config_from_dict(d: dict) -> TrainConfig:
    """Rebuild a :class:`TrainConfig` from :func:`config_to_dict`
    output. Unknown keys fail loudly (a version-skewed worker must not
    silently drop a knob it was asked to honor)."""
    kw = {}
    for k, v in d.items():
        if k in _SUBCONFIGS:
            kw[k] = _SUBCONFIGS[k](**v)
        else:
            kw[k] = v
    cfg = TrainConfig(**kw)
    # JSON has no tuples; restore the fields typed as such.
    cfg.serve.buckets = tuple(cfg.serve.buckets)
    return cfg


def reference_config(**overrides) -> TrainConfig:
    """The exact reference hyperparameters (faithful quirks on)."""
    cfg = TrainConfig()
    for k, v in overrides.items():
        if not hasattr(cfg, k):
            raise AttributeError(f"unknown TrainConfig field {k!r}")
        setattr(cfg, k, v)
    return cfg


def fixed_config(**overrides) -> TrainConfig:
    """Reference hyperparameters with the quirks fixed (sane defaults)."""
    cfg = reference_config(**overrides)
    cfg.model.logit_relu = False
    cfg.optim.dead_lr_decay = False
    cfg.data.random_crop = True
    cfg.data.random_flip = True
    cfg.data.normalize = "standardize"
    cfg.eval_full_test_set = True
    return cfg
