"""dml_cnn_cifar10_tpu — a TPU-native distributed CNN training framework.

A ground-up JAX/XLA/pjit/Pallas re-design of the capabilities of the
reference repo ``Huzo/Distributed-Machine-Learning-using-CNN-CIFAR-10-dataset-``
(a TF1 parameter-server CIFAR-10 CNN trainer, ``cifar10cnn.py``).

Layers (the reference's implicit TF-runtime layers made explicit):

- :mod:`~dml_cnn_cifar10_tpu.data`     — host-side input pipeline
  (replaces TF queue runners / FixedLengthRecordReader,
  reference ``cifar10cnn.py:54-91``).
- :mod:`~dml_cnn_cifar10_tpu.ops`      — XLA/Pallas compute primitives
  (replaces TF C++ op kernels invoked at ``cifar10cnn.py:107-145``).
- :mod:`~dml_cnn_cifar10_tpu.models`   — model zoo (reference CNN at parity,
  plus the config ladder: CIFAR-100 head, ResNet, ViT).
- :mod:`~dml_cnn_cifar10_tpu.train`    — loss/optimizer/metrics/driver
  (reference ``cifar10cnn.py:150-176,228-242``).
- :mod:`~dml_cnn_cifar10_tpu.parallel` — mesh/pjit/collectives/multi-host
  (replaces the gRPC PS cluster, ``cifar10cnn.py:184-196``).
- :mod:`~dml_cnn_cifar10_tpu.ckpt`     — checkpoint/restore
  (replaces MonitoredTrainingSession's saver, ``cifar10cnn.py:222``).
- :mod:`~dml_cnn_cifar10_tpu.compilecache` — persistent XLA executable
  cache + AOT warm-start (the explicit form of the cross-session graph
  amortization TF's runtime did implicitly; ``docs/COMPILECACHE.md``).
- :mod:`~dml_cnn_cifar10_tpu.cli`      — reference-compatible CLI
  (``cifar10cnn.py:245-274``).
"""

__version__ = "0.1.0"

from dml_cnn_cifar10_tpu.config import (  # noqa: F401
    DataConfig,
    ModelConfig,
    OptimConfig,
    ParallelConfig,
    TrainConfig,
    reference_config,
)
