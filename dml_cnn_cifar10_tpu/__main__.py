"""``python -m dml_cnn_cifar10_tpu`` — same CLI as ``cifar10cnn.py``."""

import sys

from dml_cnn_cifar10_tpu.cli.main import main

if __name__ == "__main__":
    sys.exit(main())
